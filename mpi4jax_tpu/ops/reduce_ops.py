"""Reduction operators.

The reference passes ``mpi4py.MPI.Op`` handles straight through to libmpi
(/root/reference/mpi4jax/_src/utils.py:133-152 wraps them as hashable static
params).  Here the operator set is first-class framework objects that know
how to execute on TPU: each op carries

- a *fast path* onto a fused XLA collective (``psum``/``pmax``/``pmin``) when
  one exists — these compile to single ICI collectives, and
- a generic ``combine``/``reduce`` pair for the ops XLA has no fused
  collective for (PROD, bitwise) — used by the all-gather fallback and by the
  log-step prefix-scan ladder,
- dtype admissibility (logical ops want bools, bitwise ops want integers),
- differentiability (only SUM is linear; matching the reference, which
  implements JVP/transpose for SUM only, _src/collective_ops/allreduce.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Optional

import jax.numpy as jnp
import numpy as np


@dataclass(frozen=True, eq=False)  # eq/hash are name-based, defined below
class ReduceOp:
    name: str
    # one of "sum" | "max" | "min" | None — key into the fused-collective path
    lax_kind: Optional[str]
    combine: Callable = field(compare=False)
    reduce: Callable = field(compare=False)  # reduce over axis 0 of a stack
    # "any" | "numeric" | "bool" | "integer"
    domain: str = "numeric"
    differentiable: bool = False
    # user-defined (custom_op): no native/wire code — the world tier
    # composes it from allgather + a local fold, the mesh tier uses the
    # generic gather+reduce path
    custom: bool = False

    def __repr__(self):
        return f"ReduceOp({self.name})"

    def __hash__(self):
        return hash(("mpi4jax_tpu.ReduceOp", self.name))

    def __eq__(self, other):
        return isinstance(other, ReduceOp) and other.name == self.name

    def check_dtype(self, dtype):
        d = np.dtype(dtype)
        if self.domain == "numeric" and d == np.bool_:
            raise TypeError(
                f"{self!r} is not defined for boolean arrays; use LAND/LOR/LXOR"
            )
        if self.domain == "integer" and not (
            np.issubdtype(d, np.integer) or d == np.bool_
        ):
            raise TypeError(f"{self!r} requires an integer dtype, got {d.name}")
        if self.domain == "bool" and not (
            d == np.bool_ or np.issubdtype(d, np.integer)
        ):
            raise TypeError(
                f"{self!r} requires a boolean or integer dtype, got {d.name}"
            )


SUM = ReduceOp(
    "SUM", "sum", lambda a, b: a + b, lambda s: jnp.sum(s, axis=0),
    differentiable=True,
)
PROD = ReduceOp("PROD", None, lambda a, b: a * b, lambda s: jnp.prod(s, axis=0))
MAX = ReduceOp("MAX", "max", jnp.maximum, lambda s: jnp.max(s, axis=0))
MIN = ReduceOp("MIN", "min", jnp.minimum, lambda s: jnp.min(s, axis=0))
LAND = ReduceOp(
    "LAND", None, jnp.logical_and, lambda s: jnp.all(s, axis=0), domain="bool"
)
LOR = ReduceOp(
    "LOR", None, jnp.logical_or, lambda s: jnp.any(s, axis=0), domain="bool"
)
LXOR = ReduceOp(
    "LXOR",
    None,
    jnp.logical_xor,
    lambda s: jnp.sum(s.astype(jnp.int32), axis=0) % 2 == 1,
    domain="bool",
)
def _fold(binop):
    # Static unroll over the (small) leading axis — jnp bitwise functions are
    # not ufuncs, so there is no .reduce; the stack size is the communicator
    # size, known at trace time.
    def run(s):
        acc = s[0]
        for i in range(1, s.shape[0]):
            acc = binop(acc, s[i])
        return acc

    return run


BAND = ReduceOp(
    "BAND", None, jnp.bitwise_and, _fold(jnp.bitwise_and), domain="integer"
)
BOR = ReduceOp(
    "BOR", None, jnp.bitwise_or, _fold(jnp.bitwise_or), domain="integer"
)
BXOR = ReduceOp(
    "BXOR", None, jnp.bitwise_xor, _fold(jnp.bitwise_xor), domain="integer"
)

ALL_OPS = (SUM, PROD, MAX, MIN, LAND, LOR, LXOR, BAND, BOR, BXOR)
_BY_NAME = {op.name: op for op in ALL_OPS}
_CUSTOM_REGISTRY: dict = {}  # name -> (combine sig, reduce sig, domain)


def _capture_sig(v):
    """Type-tagged value signature: 2, 2.0 and True are *different*
    captures (they change dtype-promotion semantics under JAX)."""
    try:
        hash(v)
    except TypeError:
        return (type(v).__name__, id(v))  # unhashable capture: identity
    return (type(v).__name__, v)


def _fn_sig(fn):
    """Best-effort semantic signature of a user callable: code object,
    closure captures, and default arguments (factory-made lambdas share
    one code object but differ in their cells or ``n=n`` defaults).
    Unintrospectable values fall back to object identity — erring toward
    a loud rejection over a silent collision."""
    if fn is None:
        return None
    code = getattr(fn, "__code__", None)
    if code is None:
        return fn  # builtin / C function: identity
    cells = []
    for cell in fn.__closure__ or ():
        try:
            v = cell.cell_contents
        except ValueError:  # empty cell
            v = "<empty>"
        cells.append(_capture_sig(v))
    defaults = tuple(_capture_sig(v) for v in fn.__defaults__ or ())
    kwdefaults = tuple(
        (k, _capture_sig(v))
        for k, v in sorted((fn.__kwdefaults__ or {}).items())
    )
    return (code, tuple(cells), defaults, kwdefaults)


def custom_op(name: str, combine: Callable, *, reduce: Callable = None,
              domain: str = "any") -> ReduceOp:
    """A user-defined reduction operator (MPI_Op_create analog).

    The reference accepts arbitrary ``MPI.Op`` handles including
    user-created ones (/root/reference/mpi4jax/_src/utils.py:133-152
    wraps whatever mpi4py provides); this is the framework-native
    equivalent.

    Args:
        name: unique identifier.  Like the reference's pointer-keyed op
            handles, the op is identified by this name in cached jaxprs —
            every rank must create the op with the same name and the
            same semantics, and reusing a built-in name is rejected.
        combine: associative ``(a, b) -> c`` elementwise jax function.
            Must be associative; ring/tree schedules also assume
            commutativity (as does MPI's default ``commute=True``).
        reduce: optional stack reduction ``(n, ...) -> (...)`` over axis
            0; default: a left fold of ``combine``.
        domain: dtype admissibility, one of ``"any"`` / ``"numeric"`` /
            ``"bool"`` / ``"integer"``.

    Works with ``allreduce`` / ``reduce`` / ``scan`` on both tiers: the
    mesh tier runs the generic gather+reduce path (XLA fuses the fold);
    the world tier composes allgather + a local fold (the wire protocol
    carries no user code).  Not differentiable (matching the reference,
    where only SUM has autodiff).

    Example::

        absmax = m4j.custom_op("ABSMAX", lambda a, b:
                               jnp.maximum(jnp.abs(a), jnp.abs(b)))
        out = m4j.allreduce(x, op=absmax)
    """
    if not isinstance(name, str) or not name:
        raise TypeError(f"custom op name must be a non-empty str: {name!r}")
    if name.upper() in _BY_NAME:
        raise ValueError(
            f"{name!r} is a built-in ReduceOp name; pick a distinct one"
        )
    if domain not in ("any", "numeric", "bool", "integer"):
        raise ValueError(f"unknown domain {domain!r}")
    # Name IS the identity (stable across processes for cached jaxprs),
    # so one name must never mean two different semantics in a process:
    # jit caches key on the op's hash and would silently reuse the first
    # registration's compilation.  Re-creating the op with the same code
    # (e.g. the same lambda in a loop) is fine; differing combine/reduce
    # functions, closure captures, or domain are rejected.
    sig = (_fn_sig(combine), _fn_sig(reduce), domain)
    prior = _CUSTOM_REGISTRY.get(name)
    if prior is not None and prior != sig:
        raise ValueError(
            f"custom op {name!r} already registered with different "
            f"semantics (combine/reduce/domain); custom-op identity is "
            f"name-based — use a distinct name (or reuse the original "
            f"ReduceOp object)"
        )
    _CUSTOM_REGISTRY[name] = sig
    return ReduceOp(
        name, None, combine, reduce if reduce is not None else _fold(combine),
        domain=domain, custom=True,
    )


def as_reduce_op(op) -> ReduceOp:
    """Coerce ``op`` (ReduceOp or name string) to a ReduceOp."""
    if isinstance(op, ReduceOp):
        return op
    if isinstance(op, str) and op.upper() in _BY_NAME:
        return _BY_NAME[op.upper()]
    raise TypeError(
        f"expected a mpi4jax_tpu ReduceOp (e.g. mpi4jax_tpu.SUM), a "
        f"custom_op(...), or one of {sorted(_BY_NAME)}, got {op!r}"
    )
