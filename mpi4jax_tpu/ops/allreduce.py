"""allreduce — reduce across all ranks, result on every rank.

Reference: /root/reference/mpi4jax/_src/collective_ops/allreduce.py (user fn
:36-76, JVP/transpose :188-218 — SUM only, with the transposed pass lowering
to identity :87-89).  Mesh tier compiles to a single fused XLA collective
(``lax.psum``/``pmax``/``pmin``) over ICI; autodiff for SUM comes from
``psum``'s own linearity rules, and matches the reference's contract
(JVP = allreduce of the tangent; transpose = identity per-shard) — verified
by the double-transpose tests.
"""

from __future__ import annotations


from ..utils import validation as _validation
from . import _dispatch, _mesh_impl
from .reduce_ops import SUM, as_reduce_op


def allreduce(x, op=SUM, *, comm=None, token=None, compression=None,
              algo=None):
    """Reduce ``x`` with ``op`` across all ranks of ``comm``.

    Args:
        x: array; every rank contributes one, all ranks receive the result.
        op: a :class:`ReduceOp` (``SUM``/``PROD``/``MAX``/``MIN``/logical/
            bitwise). Only ``SUM`` is differentiable.
        comm: communicator (default: ambient).
        token: optional ordering token; if given, returns ``(result, token)``.
        compression: ``"int8"`` for the bandwidth-saving quantized path
            (SUM only, ~1e-2 relative error, both tiers;
            ops/quantized.py).
        algo: force a collective algorithm for THIS call on a world
            comm (``"ring"``/``"rd"``/``"tree"``/``"qring"``/``"qrd"``/
            ``"hring"``/``"htree"``) instead of the engine's selection.
            Every rank must force the same one; ineligible picks
            degrade exactly like table rows (``mpi4jax_tpu.tune``), and
            the schedule signature stays plain ``allreduce`` — forcing
            is invisible to the static verifier.
    """
    op = as_reduce_op(op)
    x = _validation.check_array("x", x)
    comm = _dispatch.resolve_comm(comm)

    if algo is not None:
        from .. import tune

        algo = tune._check_algo(algo, "allreduce")
        if _dispatch.is_mesh(comm):
            _validation.fail(
                "algo= forces a WORLD-tier transport schedule; the mesh "
                "tier compiles to one XLA collective",
                op="allreduce", comm=comm, x=x, exc=NotImplementedError)
        if compression is not None:
            _validation.fail(
                "compression='int8' selects its own wire format; do not "
                "combine it with algo=",
                op="allreduce", comm=comm, x=x, exc=ValueError)
        if op.custom:
            _validation.fail(
                f"custom reduce op {op.name} runs as allgather + local "
                "fold; there is no allreduce schedule to force",
                op="allreduce", comm=comm, x=x, exc=ValueError)

    if compression is not None:
        if compression != "int8":
            _validation.fail(
                f"unknown compression {compression!r}; supported: 'int8'",
                op="allreduce", comm=comm, x=x, exc=ValueError)
        if op.name != "SUM":
            _validation.fail(
                f"compression='int8' is supported with op=SUM, got "
                f"{op.name}",
                op="allreduce", comm=comm, x=x, exc=NotImplementedError)
        if _dispatch.is_mesh(comm):
            from .quantized import quantized_allreduce_sum

            body = lambda v: quantized_allreduce_sum(v, comm.axis)
            return _dispatch.maybe_tokenized(body, x, token)
        from . import _world_impl
        from .quantized import check_quantizable, native_quant_algo

        check_quantizable(x, comm)
        algo = native_quant_algo(comm, x)
        if algo is not None:
            # native in-collective path: ONE allreduce whose wire frames
            # carry int8 codes + f32 absmax scales (qring/qrd in
            # native/tpucomm.cc) — the schedule signature is still
            # "allreduce", so the verifier and the plan compiler treat
            # it exactly like the exact collective
            body = lambda v: _world_impl.allreduce(v, op, comm, algo=algo)
            return _dispatch.maybe_tokenized(
                body, x, token,
                token_fn=_world_impl.token_variant_fn(
                    "allreduce", comm=comm, op=op, algo=algo))
        from .quantized import quantized_allreduce_sum_world

        body = lambda v: quantized_allreduce_sum_world(v, comm)
        return _dispatch.maybe_tokenized(body, x, token)

    if _dispatch.is_mesh(comm):
        body = lambda v: _mesh_impl.allreduce(v, op, comm.axis)
    else:
        from . import _world_impl

        _validation.check_reduce_dtype("allreduce", op, x, comm)
        _validation.check_wire_dtype("allreduce", x, comm)
        body = lambda v: _world_impl.allreduce(v, op, comm, algo=algo)
        if op.custom:  # allgather + local fold, token-chained
            return _dispatch.maybe_tokenized(
                body, x, token,
                token_fn=_world_impl.custom_fold_token_fn(op, comm))
        return _dispatch.maybe_tokenized(
            body, x, token,
            token_fn=_world_impl.token_variant_fn(
                "allreduce", comm=comm, op=op, algo=algo))
    return _dispatch.maybe_tokenized(body, x, token)
