"""barrier — synchronize all ranks.

Reference: /root/reference/mpi4jax/_src/collective_ops/barrier.py (the only
op with no array I/O, :116-117).  A compiled SPMD program needs no barrier
for correctness; the mesh tier emits a cross-rank psum dependency so
subsequent host-visible effects are ordered after all ranks arrive.  The
world tier performs a real rendezvous in the native transport.
"""

from __future__ import annotations

from . import _dispatch, _mesh_impl


def barrier(*, comm=None, token=None):
    """Block until every rank reaches the barrier.

    Returns ``None`` (primary API) or a new token (if ``token`` given).
    """
    comm = _dispatch.resolve_comm(comm)

    if _dispatch.is_mesh(comm):
        sync = _mesh_impl.barrier(comm.axis, tie=token)
        if token is not None:
            return _dispatch.token_out(token, sync)
        return None

    from . import _world_impl

    sync = _world_impl.barrier(comm, token)
    if token is not None:
        return _dispatch.token_out(token, sync)
    return None
