"""reduce — reduce across ranks, result delivered to the root.

Reference: /root/reference/mpi4jax/_src/collective_ops/reduce.py
(rank-dependent output: root gets the reduction, other ranks get their input
back, :71-80,186-197).  Mesh tier: allreduce + per-rank select — shapes are
uniform, values rank-dependent, which is SPMD-legal; XLA's allreduce is the
same collective a rooted reduce would use on ICI anyway.
"""

from __future__ import annotations


from ..utils import validation as _validation
from . import _dispatch, _mesh_impl
from .reduce_ops import SUM, as_reduce_op


def reduce(x, op=SUM, root=0, *, comm=None, token=None):
    """Reduce ``x`` with ``op``; root receives the result, others get ``x``."""
    op = as_reduce_op(op)
    x = _validation.check_array("x", x)
    root = _validation.check_static_int("root", root)
    comm = _dispatch.resolve_comm(comm)

    if _dispatch.is_mesh(comm):
        body = lambda v: _mesh_impl.reduce(v, op, root, comm.axis)
    else:
        from . import _world_impl

        _validation.check_in_range("root", root, comm.size(),
                                   op="reduce", comm=comm)
        _validation.check_reduce_dtype("reduce", op, x, comm)
        _validation.check_wire_dtype("reduce", x, comm)
        body = lambda v: _world_impl.reduce(v, op, root, comm)
        if op.custom:  # gather + local fold at root, token-chained
            return _dispatch.maybe_tokenized(
                body, x, token,
                token_fn=_world_impl.custom_fold_token_fn(op, comm,
                                                          root=root))
        return _dispatch.maybe_tokenized(
            body, x, token,
            token_fn=_world_impl.token_variant_fn(
                "reduce", comm=comm, op=op, root=root))
    return _dispatch.maybe_tokenized(body, x, token)
