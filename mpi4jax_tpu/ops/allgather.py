"""allgather — concatenate every rank's array along a new leading axis.

Reference: /root/reference/mpi4jax/_src/collective_ops/allgather.py (output
shape ``(nproc, *in_shape)``, :100-101,181-188).  Mesh tier is a single
``lax.all_gather`` over ICI.
"""

from __future__ import annotations

from ..utils import validation as _validation
from . import _dispatch, _mesh_impl


def allgather(x, *, comm=None, token=None):
    """Gather ``x`` from all ranks; every rank receives ``(size, *x.shape)``."""
    x = _validation.check_array("x", x)
    comm = _dispatch.resolve_comm(comm)

    if _dispatch.is_mesh(comm):
        body = lambda v: _mesh_impl.allgather(v, comm.axis)
    else:
        from . import _world_impl

        _validation.check_wire_dtype("allgather", x, comm)
        body = lambda v: _world_impl.allgather(v, comm)
        return _dispatch.maybe_tokenized(
            body, x, token,
            token_fn=_world_impl.token_variant_fn("allgather", comm=comm))
    return _dispatch.maybe_tokenized(body, x, token)
