"""Pallas RDMA ring collectives — direct inter-chip DMA fast path (opt-in).

The mesh tier normally lowers collectives to XLA's fused ICI collectives
(``lax.psum`` / ``all_gather`` / ``ppermute`` — see ``_mesh_impl.py``).  This
module provides the same semantics over *explicit* Pallas async remote DMA
(``pltpu.make_async_remote_copy``), the TPU-native analog of the reference's
hand-rolled transport layer (its Cython bridge drives libmpi directly,
reference ``mpi4jax/_src/xla_bridge/mpi_xla_bridge_gpu.pyx:233-251``; here
the "transport" is the ICI DMA engine and the "rank" is a mesh position).

Why it exists:

* it gives the framework a handle on the wire protocol (chunking, direction,
  overlap) that XLA's builtin collectives don't expose — the extension point
  for fused communication/compute kernels (see ``ops/flash.py`` for the
  attention instance of that idea);
* it proves the ordering story holds without XLA's collective scheduler in
  the loop — each hop is an explicit semaphore-paired DMA.

Design: ONE kernel (``_ring_shift_kernel``) does one RDMA hop; every
collective is composed from hops in plain JAX so XLA still owns the compute
between hops (reductions, slot assembly) and can overlap it with the next
launch.  The ring algorithms are the classical bandwidth-optimal ones
(reduce-scatter + all-gather, as in the native world-tier ring in
``native/tpucomm.cc``).

All functions must be called inside ``shard_map`` with ``axis`` bound, like
everything in ``_mesh_impl``.  Off-TPU they run under Pallas TPU interpret
mode so the CPU test mesh exercises the identical code path.

Opt-in routing: set ``MPI4JAX_TPU_PALLAS_COLLECTIVES=1`` and the mesh tier
routes allreduce(SUM)/allgather/ring-sendrecv through this module (see
``_mesh_impl``); or call these functions directly.

Beyond the hop-composed collectives, this module carries the **fused
ring allreduce** (:func:`fused_ring_allreduce_sum`) — ONE kernel doing
the whole double-buffered reduce-scatter + allgather, the next remote
DMA in flight while the current chunk folds — and the **in-kernel int8
wire codec** (:func:`quant_pack_pallas`), bit-compatible with the
native ``tpucomm_quant_pack`` frame (``quant_pack_ref`` is the
contract).  Both are the data plane of the hierarchical schedules'
ICI intra-island leg (``topo/_ici_leg.py``, ``MPI4JAX_TPU_ICI_LEG``):
the fused kernel realizes EXACTLY the ``topo.simulate_ring_sum``
association (native chunk boundaries, local + incoming fold order), so
the leg is bit-comparable against the numpy simulators, and the mesh
tier's large-payload allreduce dispatch rides the same kernel.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .flash import target_platform


def _interpret(flag):
    if flag is None:
        flag = target_platform() != "tpu"
    return pltpu.InterpretParams() if flag else False


# ---------------------------------------------------------------------------
# the one kernel: k payloads, each to its own destination, all DMAs in
# flight before any wait
# ---------------------------------------------------------------------------


def _make_hop_kernel(k: int):
    """Kernel sending payload i to logical device ``dst_ref[i]``.

    Destinations are computed *outside* the kernel (they are varying values
    — ``axis_index`` arithmetic — which the VMA checker tracks in plain JAX
    but not inside kernel bodies) and arrive as SMEM scalars.  Every DMA
    starts before any wait, so payloads to distinct neighbors (e.g. the
    two ring directions) travel concurrently.
    """

    def kernel(dst_ref, *refs):
        ins, outs, sems = refs[:k], refs[k:2 * k], refs[2 * k:]
        copies = []
        for i in range(k):
            c = pltpu.make_async_remote_copy(
                src_ref=ins[i], dst_ref=outs[i],
                send_sem=sems[2 * i], recv_sem=sems[2 * i + 1],
                device_id=dst_ref[i],
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            c.start()
            copies.append(c)
        for c in copies:
            c.wait()

    return kernel


def _dst_logical_at(axis, coord):
    """Global LOGICAL device id of the device whose ``axis`` coordinate is
    ``coord`` (traced) and whose other mesh coordinates equal this
    device's."""
    mesh = jax.sharding.get_abstract_mesh()
    names = tuple(mesh.axis_names)
    if axis not in names:
        return jnp.asarray(coord, jnp.int32)
    flat = jnp.zeros((), jnp.int32)
    for name in names:
        size = mesh.shape[name]
        i = jnp.asarray(coord) if name == axis else lax.axis_index(name)
        flat = flat * size + i
    return flat.astype(jnp.int32)


def _dst_logical(axis, shift):
    """Global LOGICAL device id of rank ``me + shift`` on the ring ``axis``.

    LOGICAL ids linearize the *whole* mesh (row-major over ``axis_names``),
    so on a multi-dimensional mesh the neighbor's id depends on this
    device's coordinate on every other axis too — shifting only the ring
    axis's coordinate.  Raises if any mesh axis is not bound (e.g. a
    partially-manual shard_map); callers route through ``can_route`` first.
    """
    mesh = jax.sharding.get_abstract_mesh()
    names = tuple(mesh.axis_names)
    if axis not in names:
        n = lax.axis_size(axis)
        return jnp.mod(lax.axis_index(axis) + shift, n).astype(jnp.int32)
    flat = jnp.zeros((), jnp.int32)
    for name in names:
        size = mesh.shape[name]
        i = lax.axis_index(name)
        if name == axis:
            i = jnp.mod(i + shift, size)
        flat = flat * size + i
    return flat.astype(jnp.int32)


def can_route(axis) -> bool:
    """True when the DMA path can address the ring: a single named axis of
    a non-empty abstract mesh, with every mesh axis manual (so the global
    logical id is computable from the row-major linearization).

    ``axis in mesh.axis_names`` is required — an axis bound some other way
    (e.g. by pmap) would fall into ``_dst_logical``'s ring-coordinate
    fallback, which silently assumes ring coordinate == logical device id;
    that addressing is unverified there, so such programs keep the XLA
    collective path instead.
    """
    if not isinstance(axis, str):
        return False
    try:
        mesh = jax.sharding.get_abstract_mesh()
        if axis not in mesh.axis_names:
            return False
        for name in mesh.axis_names:
            lax.axis_index(name)
        return True
    except Exception:
        return False


def _out_struct(x, axis):
    from ..utils.jax_compat import vma_check_mode

    if vma_check_mode() is not False:
        # checked mode, or unknown (private probe gone): declaring vma is
        # correct in the former and harmlessly absorbed below in the latter
        vma = frozenset(getattr(jax.typeof(x), "vma", frozenset())) | {axis}
        try:
            return jax.ShapeDtypeStruct(x.shape, x.dtype, vma=vma)
        except TypeError:
            pass
    return jax.ShapeDtypeStruct(x.shape, x.dtype)


def _as_dma_dtype(x):
    """The DMA engines (and the interpreter) move real-typed bytes only:
    view complex as its float pair (last axis doubles)."""
    if jnp.issubdtype(x.dtype, jnp.complexfloating):
        f = jnp.float32 if x.dtype == jnp.complex64 else jnp.float64
        return x.view(f), x.dtype
    return x, None


def _hop_impl(xs, axis, dsts, interpret):
    """k paired-DMA hops: payload ``xs[i]`` to logical device ``dsts[i]``.

    The pairing contract: whichever device's hop targets *us* fills our
    corresponding output buffer; ring shifts, opposite-direction pairs,
    and XOR partners all satisfy it."""
    k = len(xs)
    viewed = [_as_dma_dtype(x) for x in xs]
    ins = tuple(v for v, _ in viewed)
    outs = pl.pallas_call(
        _make_hop_kernel(k),
        out_shape=tuple(_out_struct(x, axis) for x in ins),
        in_specs=[pl.BlockSpec(memory_space=pltpu.MemorySpace.SMEM)]
        + [pl.BlockSpec(memory_space=pl.ANY)] * k,
        out_specs=tuple(pl.BlockSpec(memory_space=pl.ANY) for _ in ins),
        scratch_shapes=[pltpu.SemaphoreType.DMA] * (2 * k),
        interpret=_interpret(interpret),
    )(jnp.stack(dsts), *ins)
    return tuple(
        o.view(c) if c is not None else o
        for o, (_, c) in zip(outs, viewed)
    )


def _ring_shift_impl(x, axis, shift, interpret):
    (out,) = _hop_impl((x,), axis, (_dst_logical(axis, shift),), interpret)
    return out


def _exchange_impl(x, axis, partner_coord, interpret):
    """Pairwise exchange with the device at ``partner_coord`` on ``axis``
    (the butterfly step; the partner relation must be an involution)."""
    (out,) = _hop_impl(
        (x,), axis, (_dst_logical_at(axis, partner_coord),), interpret
    )
    return out


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _ring_shift_d(x, axis, shift, interpret):
    return _ring_shift_impl(x, axis, shift, interpret)


def _ring_shift_fwd(x, axis, shift, interpret):
    return _ring_shift_impl(x, axis, shift, interpret), None


def _ring_shift_bwd(axis, shift, interpret, _, g):
    # the cotangent flows backward along the message edge — the source/dest
    # swap of the reference's sendrecv transpose rule
    # (/root/reference/mpi4jax/_src/collective_ops/sendrecv.py:390-409)
    return (_ring_shift_impl(g, axis, -shift, interpret),)


_ring_shift_d.defvjp(_ring_shift_fwd, _ring_shift_bwd)


def ring_shift(x, axis, shift: int = 1, *, interpret=None):
    """One RDMA hop around the ring: returns the shard of rank ``me - shift``.

    Equivalent to ``lax.ppermute(x, axis, ring_perm(n, shift))`` but executed
    as an explicit paired-semaphore remote DMA.  ``shift`` is static.

    Reverse-mode differentiable (transpose = shift by ``-shift``); fwd-mode
    raises, matching the reference's sendrecv contract
    (sendrecv.py:150-155 there).
    """
    if shift == 0:
        return x
    return _ring_shift_d(x, axis, shift, interpret)


# ---------------------------------------------------------------------------
# collectives composed from hops
# ---------------------------------------------------------------------------


def _ring_shift2_impl(a, b, axis, interpret):
    # two simultaneous hops — ``a`` to the right neighbor, ``b`` to the
    # left — so the two ICI link directions carry traffic concurrently
    # (the bidirectional-ring trick; one ``lax.ppermute`` cannot express it)
    return _hop_impl(
        (a, b), axis,
        (_dst_logical(axis, 1), _dst_logical(axis, -1)), interpret,
    )


@partial(jax.custom_vjp, nondiff_argnums=(2, 3))
def _ring_shift2_d(a, b, axis, interpret):
    return _ring_shift2_impl(a, b, axis, interpret)


def _ring_shift2_fwd(a, b, axis, interpret):
    return _ring_shift2_impl(a, b, axis, interpret), None


def _ring_shift2_bwd(axis, interpret, _, g):
    # our `a` went right, so its cotangent comes back from the right
    # neighbor (and b's from the left): one bidirectional hop with the
    # payloads swapped onto the opposite directions
    ga, gb = g
    back_b, back_a = _ring_shift2_impl(gb, ga, axis, interpret)
    return (back_a, back_b)


_ring_shift2_d.defvjp(_ring_shift2_fwd, _ring_shift2_bwd)


def ring_shift2(a, b, axis, *, interpret=None):
    """One bidirectional ring step: returns ``(a', b')`` where ``a'`` is the
    left neighbor's ``a`` (data moved right) and ``b'`` the right
    neighbor's ``b`` (data moved left).  Reverse-mode differentiable;
    fwd-mode raises."""
    return _ring_shift2_d(a, b, axis, interpret)


def _ring_shift_n_impl(xs, axis, shift, interpret):
    dst = _dst_logical(axis, shift)
    return _hop_impl(tuple(xs), axis, (dst,) * len(xs), interpret)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2, 3))
def _ring_shift_n_d(xs, axis, shift, interpret):
    return _ring_shift_n_impl(xs, axis, shift, interpret)


def _ring_shift_n_fwd(xs, axis, shift, interpret):
    return _ring_shift_n_impl(xs, axis, shift, interpret), None


def _ring_shift_n_bwd(axis, shift, interpret, _, g):
    return (_ring_shift_n_impl(tuple(g), axis, -shift, interpret),)


_ring_shift_n_d.defvjp(_ring_shift_n_fwd, _ring_shift_n_bwd)


def ring_shift_n(xs, axis, shift: int = 1, *, interpret=None):
    """Shift a tuple of arrays one ring hop together — every payload's DMA
    is in flight before any wait.  The batched-ICI analog of the k/v
    rotation in ring attention.  Reverse-mode differentiable."""
    if shift == 0:
        return tuple(xs)
    return _ring_shift_n_d(tuple(xs), axis, shift, interpret)


def _all_gather_impl(x, axis, interpret):
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    if n == 1:
        return x[None]

    def hop(cur, _):
        nxt = _ring_shift_impl(cur, axis, 1, interpret)
        return nxt, nxt

    # After s hops the carried shard originated at rank (me - s) % n.
    _, received = lax.scan(hop, x, None, length=n - 1)
    stacked = jnp.concatenate([x[None], received], axis=0)
    # stacked[s] is rank (me - s)'s shard; row j of the result wants rank
    # j's shard, i.e. s = (me - j) % n.
    src = jnp.mod(me - jnp.arange(n), n)
    return jnp.take(stacked, src, axis=0)


def _rs_chunk_index(me, s, n, direction):
    # chunk forwarded at step s; derived so the fully-reduced chunk that
    # lands after n-1 hops is exactly chunk ``me`` for either direction
    return jnp.mod(me - direction * (1 + s), n)


def _reduce_scatter_impl(x, axis, interpret):
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    if x.shape[0] % n:
        raise ValueError(
            f"reduce_scatter_sum requires leading axis divisible by the ring "
            f"size ({n}), got shape {x.shape}"
        )
    if n == 1:
        return x
    view = x.reshape((n, x.shape[0] // n) + x.shape[1:])

    def chunk(s):
        return jnp.take(view, _rs_chunk_index(me, s, n, 1), axis=0)

    def step(partial_, s):
        recv = _ring_shift_impl(partial_, axis, 1, interpret)
        return chunk(s) + recv, None

    out, _ = lax.scan(step, chunk(0), jnp.arange(1, n))
    return out


def _reduce_scatter_bidir(a, b, axis, interpret):
    """Fused bidirectional reduce-scatter: segment ``a`` rides the ring
    rightward, ``b`` leftward, both hops in one kernel per step."""
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    va = a.reshape((n, a.shape[0] // n) + a.shape[1:])
    vb = b.reshape((n, b.shape[0] // n) + b.shape[1:])

    def step(carry, s):
        pa, pb = carry
        ra, rb = ring_shift2(pa, pb, axis, interpret=interpret)
        na = jnp.take(va, _rs_chunk_index(me, s, n, 1), axis=0) + ra
        nb = jnp.take(vb, _rs_chunk_index(me, s, n, -1), axis=0) + rb
        return (na, nb), None

    init = (
        jnp.take(va, _rs_chunk_index(me, 0, n, 1), axis=0),
        jnp.take(vb, _rs_chunk_index(me, 0, n, -1), axis=0),
    )
    (oa, ob), _ = lax.scan(step, init, jnp.arange(1, n))
    return oa, ob


def _all_gather_bidir(a, b, axis, interpret):
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)

    def hop(carry, _):
        ca, cb = carry
        nxt = ring_shift2(ca, cb, axis, interpret=interpret)
        return nxt, nxt

    _, (ras, rbs) = lax.scan(hop, (a, b), None, length=n - 1)
    stacked_a = jnp.concatenate([a[None], ras], axis=0)
    stacked_b = jnp.concatenate([b[None], rbs], axis=0)
    ja = jnp.mod(me - jnp.arange(n), n)
    jb = jnp.mod(jnp.arange(n) - me, n)
    return (jnp.take(stacked_a, ja, axis=0),
            jnp.take(stacked_b, jb, axis=0))


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _all_gather_d(x, axis, interpret):
    return _all_gather_impl(x, axis, interpret)


def _all_gather_fwd(x, axis, interpret):
    return _all_gather_impl(x, axis, interpret), x.shape


def _all_gather_bwd(axis, interpret, x_shape, g):
    # y_r[j] = x_j on every rank r, so dx = sum_r g_r[me]: exactly this
    # rank's chunk of a reduce-scatter over the stacked cotangent rows
    # (row boundaries and chunk boundaries coincide after flattening).
    dx = _reduce_scatter_impl(g.reshape((g.size,)), axis, interpret)
    return (dx.reshape(x_shape),)


_all_gather_d.defvjp(_all_gather_fwd, _all_gather_bwd)


def all_gather(x, axis, *, interpret=None):
    """Ring all-gather: returns ``(n, *x.shape)``, row r = rank r's shard.

    n-1 hops of one shard each — the bandwidth-optimal schedule (each byte
    crosses each link exactly once), matching ``lax.all_gather`` semantics
    (reference op: ``mpi4jax/_src/collective_ops/allgather.py``).
    Reverse-mode differentiable (transpose = reduce-scatter); fwd-mode
    raises (⊃ the reference, whose allgather has no autodiff at all).
    """
    return _all_gather_d(x, axis, interpret)


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _reduce_scatter_d(x, axis, interpret):
    return _reduce_scatter_impl(x, axis, interpret)


def _reduce_scatter_fwd(x, axis, interpret):
    return _reduce_scatter_impl(x, axis, interpret), x.shape


def _reduce_scatter_bwd(axis, interpret, x_shape, g):
    # y_me = sum_r x_r[chunk me] ⇒ dx[chunk j] = g_j: an all-gather of the
    # per-rank cotangent chunks laid back out along the leading axis.
    rows = _all_gather_impl(g, axis, interpret)
    return (rows.reshape(x_shape),)


_reduce_scatter_d.defvjp(_reduce_scatter_fwd, _reduce_scatter_bwd)


def reduce_scatter_sum(x, axis, *, interpret=None):
    """Ring reduce-scatter (SUM): ``x`` is ``(n*c, ...)``; returns this
    rank's fully-reduced chunk ``(c, ...)`` (chunk index = rank).

    Classical ring: at step s each rank forwards the partial for chunk
    ``(me - 1 - s) % n``, adding its own contribution as the partial passes
    through — after n-1 hops chunk ``me`` has visited every rank.
    Reverse-mode differentiable (transpose = all-gather); fwd-mode raises.
    """
    return _reduce_scatter_d(x, axis, interpret)


@partial(jax.custom_vjp, nondiff_argnums=(1,))
def allreduce_sum(x, axis):
    """Ring allreduce (SUM) = reduce-scatter + all-gather over RDMA hops.

    Semantics match ``lax.psum(x, axis)`` / the mesh tier's allreduce-SUM
    (reference op: ``mpi4jax/_src/collective_ops/allreduce.py:41-76``); like
    the reference's autodiff support it is SUM-only, and the cotangent of an
    allreduce-SUM is again an allreduce-SUM (``allreduce.py:188-218``).
    """
    return _allreduce_sum(x, axis)


def _make_alltoall_kernel(n: int):
    """Direct all-to-all: row i of the local input goes straight to rank
    i's output (landing at the row indexed by *our* rank) — n simultaneous
    DMAs, one network hop, no ring.  Message from sender s lands in our
    row s and signals our recv semaphore slot s, so each transfer has an
    unambiguous (row, semaphore) pair.  The i == me row is a loopback
    remote copy to our own logical id — deliberately: ``me`` is a traced
    scalar, so special-casing it would put a predicated branch in an
    otherwise uniform descriptor loop to save one local-loop descriptor;
    correctness is identical either way."""

    def kernel(meta_ref, x_ref, o_ref, send_sems, recv_sems):
        me = meta_ref[0]
        copies = []
        for i in range(n):
            c = pltpu.make_async_remote_copy(
                src_ref=x_ref.at[i],
                dst_ref=o_ref.at[me],
                send_sem=send_sems.at[i],
                recv_sem=recv_sems.at[me],
                device_id=meta_ref[1 + i],
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            c.start()
            copies.append(c)
        for c in copies:
            c.wait_send()
        for j in range(n):
            # wait for sender j's row: a local descriptor of the same
            # extent waits the matching byte count on slot j
            pltpu.make_async_copy(
                o_ref.at[j], o_ref.at[j], recv_sems.at[j]
            ).wait()

    return kernel


def _alltoall_impl(x, axis, interpret):
    n = lax.axis_size(axis)
    if x.ndim < 1 or x.shape[0] != n:
        raise ValueError(
            f"alltoall requires leading axis == ring size ({n}), got shape "
            f"{x.shape}"
        )
    if n == 1:
        return x
    me = lax.axis_index(axis).astype(jnp.int32)
    meta = jnp.stack(
        [me] + [_dst_logical_at(axis, i) for i in range(n)]
    )
    v, cdtype = _as_dma_dtype(x)
    out = pl.pallas_call(
        _make_alltoall_kernel(n),
        out_shape=_out_struct(v, axis),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.MemorySpace.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA((n,)),
            pltpu.SemaphoreType.DMA((n,)),
        ],
        interpret=_interpret(interpret),
    )(meta, v)
    return out.view(cdtype) if cdtype is not None else out


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _alltoall_d(x, axis, interpret):
    return _alltoall_impl(x, axis, interpret)


def _alltoall_fwd(x, axis, interpret):
    return _alltoall_impl(x, axis, interpret), None


def _alltoall_bwd(axis, interpret, _, g):
    # out[j] = x_j[me] on every rank: the cotangent of row i is what rank i
    # holds for us — another all-to-all (the op is its own transpose)
    return (_alltoall_impl(g, axis, interpret),)


_alltoall_d.defvjp(_alltoall_fwd, _alltoall_bwd)


def alltoall(x, axis, *, interpret=None):
    """Direct RDMA all-to-all: ``x`` is ``(n, ...)``; returns ``(n, ...)``
    where row j is rank j's row addressed to this rank — the semantics of
    ``lax.all_to_all(split_axis=0, concat_axis=0)`` / MPI_Alltoall
    (reference op: ``mpi4jax/_src/collective_ops/alltoall.py:39-83``), in
    ONE network hop instead of a ring.  Reverse-mode differentiable (the
    op is its own transpose); fwd-mode raises."""
    return _alltoall_d(x, axis, interpret)


# Above this many elements the allreduce splits the payload in half and
# runs both ring directions concurrently (each hop moves half the bytes on
# each ICI link direction — ~2x effective bandwidth on a real ring).
BIDIR_MIN_ELEMS = 16 * 1024

# Below this many elements the allreduce is latency-bound, so it takes the
# recursive-doubling butterfly — log2(n) full-payload exchanges instead of
# 2(n-1) chunk hops (requires power-of-two ring size).
BUTTERFLY_MAX_ELEMS = 4 * 1024


def _allreduce_butterfly(flat, axis, interpret):
    n = lax.axis_size(axis)
    me = lax.axis_index(axis)
    acc = flat
    k = 1
    while k < n:
        partner = jnp.bitwise_xor(me, k)
        acc = acc + _exchange_impl(acc, axis, partner, interpret)
        k *= 2
    return acc


def _allreduce_sum(x, axis, *, interpret=None):
    n = lax.axis_size(axis)
    if n == 1:
        return x
    flat = x.reshape(-1)
    if flat.shape[0] <= BUTTERFLY_MAX_ELEMS and (n & (n - 1)) == 0:
        return _allreduce_butterfly(flat, axis, interpret).reshape(x.shape)
    if flat.shape[0] >= BIDIR_MIN_ELEMS and n > 2:
        # bandwidth-bound: the fused double-buffered ring — one kernel
        # launch for all 2(n-1) hops, the next chunk's remote DMA in
        # flight while the current one folds (the hop-composed bidir
        # pair this replaced paid a kernel launch per hop; the split
        # halves survive in _reduce_scatter_bidir/_all_gather_bidir
        # for direct use)
        return _fused_ring_allreduce_impl(x, axis, interpret)
    else:
        pad = (-flat.shape[0]) % n
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
        mine = reduce_scatter_sum(flat, axis, interpret=interpret)
        full = all_gather(mine, axis, interpret=interpret).reshape(-1)
    if pad:
        full = full[: flat.shape[0] - pad]
    return full.reshape(x.shape)


def _allreduce_fwd(x, axis):
    return _allreduce_sum(x, axis), None


def _allreduce_bwd(axis, _, g):
    return (_allreduce_sum(g, axis),)


allreduce_sum.defvjp(_allreduce_fwd, _allreduce_bwd)


# ---------------------------------------------------------------------------
# fused double-buffered ring allreduce — ONE kernel, DMA/compute overlap
# ---------------------------------------------------------------------------


def _make_fused_ring_kernel(n: int, cr: int):
    """The whole ring allreduce in one kernel: a double-buffered
    reduce-scatter (the next hop's remote DMA in flight while the
    current chunk folds) followed by the allgather, ``n - 1`` hops each.

    Buffers are ``(n * cr, 128)`` with chunk ``i`` at rows
    ``[i*cr, (i+1)*cr)`` — the caller lays the native ``_chunk_lo``
    chunks out zero-padded so the fold association is EXACTLY
    ``topo.simulate_ring_sum``'s (local + incoming, ring arrival
    order).

    Reduce-scatter flow control: arrivals land in a 2-slot ``landing``
    scratch; a slot is reused at step ``s + 2``, so after folding slot
    ``s % 2`` the receiver returns a credit DMA to its LEFT neighbor,
    and a sender past step 1 waits for that credit before starting —
    the classical 2-deep producer/consumer handshake (sends at steps
    ``0..n-4`` are pre-credited by the double buffer itself).  The
    allgather needs none of this: step ``t`` forwards the chunk that
    fully landed at step ``t - 1`` into its OWN rows on the receiver,
    so regions never alias and per-step semaphores give exact
    accounting."""

    def kernel(meta_ref, x_ref, o_ref, landing, credit,
               rs_send, rs_recv, cr_send, cr_recv, ag_send, ag_recv):
        me = meta_ref[0]
        right = meta_ref[1]
        left = meta_ref[2]
        o_ref[...] = x_ref[...]
        pending = [None, None]
        pending_cr = [None, None]
        for s in range(n - 1):
            slot = s % 2
            sc = jnp.mod(me - s, n)
            rc = jnp.mod(me - 1 - s, n)
            if s >= 2:
                # the credit our left-hand receiver sent after folding
                # arrival s-2 frees its landing slot AND our send sem
                pltpu.make_async_copy(
                    credit.at[slot * 8:slot * 8 + 8, :],
                    credit.at[slot * 8:slot * 8 + 8, :],
                    cr_recv.at[slot],
                ).wait()
                pending[slot].wait_send()
            c = pltpu.make_async_remote_copy(
                src_ref=o_ref.at[pl.ds(sc * cr, cr), :],
                dst_ref=landing.at[slot * cr:(slot + 1) * cr, :],
                send_sem=rs_send.at[slot],
                recv_sem=rs_recv.at[slot],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            c.start()
            pending[slot] = c
            # wait for OUR arrival of this step, then fold it
            pltpu.make_async_copy(
                landing.at[slot * cr:(slot + 1) * cr, :],
                landing.at[slot * cr:(slot + 1) * cr, :],
                rs_recv.at[slot],
            ).wait()
            o_ref[pl.ds(rc * cr, cr), :] = (
                o_ref[pl.ds(rc * cr, cr), :]
                + landing[slot * cr:(slot + 1) * cr, :]
            )
            if s <= n - 4:
                # landing slot drained: credit our left neighbor's
                # step-(s+2) send (content is a doorbell, not data)
                if s >= 2:
                    pending_cr[slot].wait_send()
                cc = pltpu.make_async_remote_copy(
                    src_ref=credit.at[slot * 8:slot * 8 + 8, :],
                    dst_ref=credit.at[slot * 8:slot * 8 + 8, :],
                    send_sem=cr_send.at[slot],
                    recv_sem=cr_recv.at[slot],
                    device_id=left,
                    device_id_type=pltpu.DeviceIdType.LOGICAL,
                )
                cc.start()
                pending_cr[slot] = cc
        for c in pending:
            if c is not None:
                c.wait_send()
        for cc in pending_cr:
            if cc is not None:
                cc.wait_send()
        # allgather: after the reduce-scatter rank me owns chunk
        # (me+1)%n; step t forwards chunk (me+1-t)%n (own, then the one
        # that landed at step t-1) and waits for (me-t)%n from the left
        ag_copies = []
        for t in range(n - 1):
            k = jnp.mod(me + 1 - t, n)
            c = pltpu.make_async_remote_copy(
                src_ref=o_ref.at[pl.ds(k * cr, cr), :],
                dst_ref=o_ref.at[pl.ds(k * cr, cr), :],
                send_sem=ag_send.at[t],
                recv_sem=ag_recv.at[t],
                device_id=right,
                device_id_type=pltpu.DeviceIdType.LOGICAL,
            )
            c.start()
            ag_copies.append(c)
            rk = jnp.mod(me - t, n)
            pltpu.make_async_copy(
                o_ref.at[pl.ds(rk * cr, cr), :],
                o_ref.at[pl.ds(rk * cr, cr), :],
                ag_recv.at[t],
            ).wait()
        for c in ag_copies:
            c.wait_send()

    return kernel


def _fused_ring_layout(count: int, n: int):
    """Native chunk geometry: ``per``-element ``_chunk_lo`` chunks, each
    zero-padded to ``cpad`` (a lane multiple) so chunk boundaries land
    on row boundaries of the ``(n*cr, 128)`` kernel buffer."""
    per = -(-count // n)
    cpad = max(-(-per // 128) * 128, 128)
    return per, cpad, cpad // 128


def _fused_ring_allreduce_impl(x, axis, interpret):
    n = lax.axis_size(axis)
    if n == 1:
        return x
    v, cdtype = _as_dma_dtype(x)
    flat = v.reshape(-1)
    count = flat.shape[0]
    if count == 0:
        return x
    per, cpad, cr = _fused_ring_layout(count, n)
    pieces = []
    for i in range(n):
        lo, hi = min(per * i, count), min(per * (i + 1), count)
        seg = flat[lo:hi]
        if hi - lo < cpad:
            seg = jnp.concatenate(
                [seg, jnp.zeros((cpad - (hi - lo),), flat.dtype)])
        pieces.append(seg)
    buf = jnp.concatenate(pieces).reshape(n * cr, 128)
    me = lax.axis_index(axis).astype(jnp.int32)
    meta = jnp.stack([me, _dst_logical(axis, 1), _dst_logical(axis, -1)])
    out = pl.pallas_call(
        _make_fused_ring_kernel(n, cr),
        out_shape=_out_struct(buf, axis),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.MemorySpace.SMEM),
            pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM),
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.MemorySpace.VMEM),
        scratch_shapes=[
            pltpu.VMEM((2 * cr, 128), buf.dtype),
            pltpu.VMEM((16, 128), jnp.float32),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((n - 1,)),
            pltpu.SemaphoreType.DMA((n - 1,)),
        ],
        interpret=_interpret(interpret),
    )(meta, buf)
    rows = out.reshape(n, cpad)
    segs = [rows[i, :min(per * (i + 1), count) - min(per * i, count)]
            for i in range(n)]
    res = jnp.concatenate(segs).reshape(v.shape)
    return res.view(cdtype).reshape(x.shape) if cdtype is not None else res


@partial(jax.custom_vjp, nondiff_argnums=(1, 2))
def _fused_ring_d(x, axis, interpret):
    return _fused_ring_allreduce_impl(x, axis, interpret)


def _fused_ring_fwd(x, axis, interpret):
    return _fused_ring_allreduce_impl(x, axis, interpret), None


def _fused_ring_bwd(axis, interpret, _, g):
    # the cotangent of an allreduce-SUM is an allreduce-SUM
    return (_fused_ring_allreduce_impl(g, axis, interpret),)


_fused_ring_d.defvjp(_fused_ring_fwd, _fused_ring_bwd)


def fused_ring_allreduce_sum(x, axis, *, interpret=None):
    """Ring allreduce (SUM) in ONE fused kernel: double-buffered
    reduce-scatter (next hop's remote DMA overlaps the current fold)
    + allgather, with the native ``_chunk_lo`` chunk layout so the f32
    result is bit-identical to ``topo.simulate_ring_sum`` over the
    ring's per-rank inputs — the bit-parity contract the ICI
    intra-island leg (``topo/_ici_leg.py``) is verified against.
    Reverse-mode differentiable; fwd-mode raises."""
    return _fused_ring_d(x, axis, interpret)


# ---------------------------------------------------------------------------
# in-kernel int8 wire codec — bit-compatible with tpucomm_quant_pack
# ---------------------------------------------------------------------------


def _quant_pack_kernel(x_ref, scale_ref, codes_ref):
    """One shot of the native wire codec's quantize step, every
    intermediate forced to f32 exactly as ``quant_pack_ref`` (the
    numpy contract of ``tpucomm_quant_pack``) computes it: per-256
    absmax -> scale (amax/127, 1.0 for all-zero blocks) -> clip to
    [-127, 127] -> round-half-even to int8.  IEEE f32 arithmetic is
    deterministic, so the codes and scales are bit-identical to the
    reference on every backend (interpret mode included)."""
    x = x_ref[...]
    amax = jnp.max(jnp.abs(x), axis=1, keepdims=True)
    scale = jnp.where(amax > 0, amax / jnp.float32(127.0),
                      jnp.float32(1.0)).astype(jnp.float32)
    inv = (jnp.float32(1.0) / scale).astype(jnp.float32)
    v = (x * inv).astype(jnp.float32)
    v = jnp.clip(v, jnp.float32(-127.0), jnp.float32(127.0))
    codes_ref[...] = jnp.round(v).astype(jnp.int8)
    scale_ref[...] = scale


def quant_pack_pallas(x, *, interpret=None):
    """The native int8 wire frame of a 1-D f32 array, quantized
    IN-KERNEL: ``ceil(n/256)`` f32 block scales (bitcast to their
    little-endian int8 bytes) followed by ``n`` int8 codes — the exact
    ``tpucomm_quant_pack`` layout (``bridge.quant_packed_bytes``
    bytes).  Bit-compatibility with ``quant_pack_ref`` is
    test-enforced (the cross-ISA bit-identity suite); the quantized
    ICI leg ships these bytes to the leader leg with no host-side
    pack."""
    from .quantized import QUANT_BLOCK

    flat = jnp.asarray(x, jnp.float32).reshape(-1)
    count = flat.shape[0]
    if count == 0:
        return jnp.zeros((0,), jnp.int8)
    nb = -(-count // QUANT_BLOCK)
    pad = nb * QUANT_BLOCK - count
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    scales, codes = pl.pallas_call(
        _quant_pack_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((nb, 1), jnp.float32),
            jax.ShapeDtypeStruct((nb, QUANT_BLOCK), jnp.int8),
        ),
        interpret=_interpret(interpret),
    )(flat.reshape(nb, QUANT_BLOCK))
    sbytes = lax.bitcast_convert_type(
        scales.reshape(nb), jnp.int8).reshape(-1)
    return jnp.concatenate([sbytes, codes.reshape(-1)[:count]])


# ---------------------------------------------------------------------------
# mesh-tier routing helpers
# ---------------------------------------------------------------------------


def ring_shift_of(perm, size: int):
    """If ``perm`` is exactly the ring pattern ``i -> (i+k) % n`` for some
    nonzero k, return k; else None.  Used by the mesh tier to route eligible
    ``sendrecv`` permutations through the DMA path."""
    pairs = set((int(a), int(b)) for a, b in perm)
    if len(pairs) != size:
        return None
    shifts = set((b - a) % size for a, b in pairs)
    if len(shifts) != 1:
        return None
    k = shifts.pop()
    if k == 0:
        return None
    if pairs != {(i, (i + k) % size) for i in range(size)}:
        return None
    return k
