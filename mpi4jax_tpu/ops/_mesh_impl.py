"""Mesh/ICI-tier implementations of the 12 ops as XLA collectives.

This is the TPU-native core of the framework.  Where the reference lowers
every op to a host custom call into libmpi
(/root/reference/mpi4jax/_src/collective_ops/*.py → mpi_xla_bridge.pyx), here
each op *is* an XLA collective inside ``shard_map``: the compiler schedules it
onto ICI, fuses around it, and — because every rank runs the same SPMD
program — ordering and deadlock-freedom hold by construction (the property
the reference's token system exists to provide, docs/sharp-bits.rst there).

All functions below must be called inside ``shard_map`` (or ``spmd``) with
``axis`` bound.  ``rank`` is ``lax.axis_index(axis)`` (traced, uniform
program), ``size`` is ``lax.axis_size(axis)`` (static).

Collective mapping (reference op → XLA collective):

==============  =====================================================
allreduce       ``lax.psum/pmax/pmin``; generic ops all-gather+reduce
allgather       ``lax.all_gather(axis=0)``
alltoall        ``lax.all_to_all(split_axis=0, concat_axis=0)``
bcast           masked ``psum`` (only root contributes)
reduce          allreduce + select (non-root keeps its input)
scan            Hillis–Steele ladder of ``lax.ppermute`` (log2 steps)
scatter         ``lax.all_to_all`` + static root row
gather          allgather (result replicated — SPMD divergence, DESIGN.md)
sendrecv        ``lax.ppermute``
barrier         cross-rank psum dependency (SPMD programs need no barrier)
send/recv       rejected — meaningless as separate calls in one SPMD
                program; world tier provides exact reference semantics
==============  =====================================================
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..utils import config as _config
from ..utils import dtypes as _dtypes
from .reduce_ops import ReduceOp, SUM


def _pallas_ring(axis):
    """True when the Pallas RDMA fast path should handle this collective:
    opt-in flag set, ``axis`` is a single named axis, and the global
    logical device id of a ring neighbor is computable (every mesh axis
    bound — see ``pallas_collectives.can_route``).  Under the flag the
    routed ops are reverse-mode differentiable only (fwd-mode raises,
    like the reference's sendrecv, sendrecv.py:150-155 there)."""
    if not _config.pallas_collectives_enabled():
        return False
    from . import pallas_collectives as _pc

    return _pc.can_route(axis)


def _rank(axis):
    return lax.axis_index(axis)


def _size(axis) -> int:
    return lax.axis_size(axis)


def _axes_tuple(axis):
    return (axis,) if isinstance(axis, str) else tuple(axis)


def as_varying(x, axis):
    """Promote ``x`` to be varying over ``axis`` (VMA bookkeeping).

    Under ``shard_map(..., check_vma=True)`` (the default), collectives
    require their operand's varying-axes set to include the collective
    axis; constants and replicated closures arrive invarying.  This makes
    every op accept either, so the ops work in user shard_maps regardless
    of the check mode.
    """
    from ..utils.jax_compat import vma_check_mode

    checked = vma_check_mode()
    if checked is None:
        # a wrong guess either corrupts transposed programs (pcast under
        # unchecked shard_map) or trips collective vma errors — fail loud
        raise RuntimeError(
            "cannot determine shard_map's check_vma mode (private jax API "
            "moved); update mpi4jax_tpu.utils.jax_compat.vma_check_mode"
        )
    if not checked:
        # unchecked shard_map: vma is untracked (always empty) and pcast's
        # transpose (a psum) would corrupt/abort transposed programs
        return x
    try:
        vma = jax.typeof(x).vma
    except (AttributeError, TypeError):
        return x
    missing = tuple(a for a in _axes_tuple(axis) if a not in vma)
    if missing:
        x = lax.pcast(x, missing, to="varying")
    return x


def _masked(x, keep):
    """x where keep (scalar traced bool) else zeros, preserving dtype."""
    return jnp.where(keep, x, jnp.zeros_like(x))


def allreduce(x, op: ReduceOp, axis):
    op.check_dtype(x.dtype)
    x = as_varying(x, axis)
    if op.lax_kind == "sum":
        if _pallas_ring(axis):
            from . import pallas_collectives as _pc

            # bandwidth-bound payloads dispatch to the fused
            # double-buffered ring kernel inside (one launch for all
            # hops; the same data plane the hierarchical schedules'
            # ICI intra leg rides — topo/_ici_leg.py)
            return _pc.allreduce_sum(x, axis)
        return lax.psum(x, axis)
    if op.lax_kind == "max":
        return lax.pmax(x, axis)
    if op.lax_kind == "min":
        return lax.pmin(x, axis)
    if op.custom:
        # user-defined: always the generic gather+reduce path — the
        # domain-based fast paths below are for the named builtins only
        stacked = lax.all_gather(x, axis, axis=0, tiled=False)
        return op.reduce(stacked).astype(x.dtype)
    if op.domain == "bool":
        # Logical ops ride the fused min/max collectives on a 0/1 view
        # (truthiness, so integer inputs behave like MPI's logical ops).
        bits = (x != 0).astype(jnp.uint8)
        if op.name == "LAND":
            out = lax.pmin(bits, axis)
        elif op.name == "LOR":
            out = lax.pmax(bits, axis)
        else:  # LXOR: parity of the count of true values
            out = (lax.psum(bits.astype(jnp.uint32), axis) % 2).astype(jnp.uint8)
        return out.astype(x.dtype)
    # PROD / bitwise: no fused XLA collective — gather then reduce locally.
    stacked = lax.all_gather(x, axis, axis=0, tiled=False)
    return op.reduce(stacked).astype(x.dtype)


def allgather(x, axis):
    x = as_varying(x, axis)
    if _pallas_ring(axis):
        from . import pallas_collectives as _pc

        return _pc.all_gather(x, axis)
    return lax.all_gather(x, axis, axis=0, tiled=False)


def alltoall(x, axis):
    size = _size(axis)
    if x.ndim < 1 or x.shape[0] != size:
        raise ValueError(
            f"alltoall requires leading axis == communicator size ({size}), "
            f"got shape {x.shape}"
        )
    x = as_varying(x, axis)
    if _pallas_ring(axis):
        from . import pallas_collectives as _pc

        return _pc.alltoall(x, axis)
    return lax.all_to_all(x, axis, split_axis=0, concat_axis=0)


def bcast(x, root: int, axis):
    _dtypes.check_supported(x.dtype)
    x = as_varying(x, axis)
    r = _rank(axis)
    if x.dtype == jnp.bool_:
        return lax.psum(_masked(x.astype(jnp.uint8), r == root), axis) != 0
    return lax.psum(_masked(x, r == root), axis)


def reduce(x, op: ReduceOp, root: int, axis):
    # Reference contract: root receives the reduction, other ranks get their
    # input back unchanged (rank-dependent *values*, uniform shapes — SPMD ok).
    x = as_varying(x, axis)
    full = as_varying(allreduce(x, op, axis), axis)
    return jnp.where(_rank(axis) == root, full, x)


def gather(x, root: int, axis):
    # SPMD divergence (DESIGN.md): result (size, *shape) is materialized on
    # every rank; the root's view equals the reference's root result.
    del root
    return lax.all_gather(as_varying(x, axis), axis, axis=0, tiled=False)


def scatter(x, root: int, axis):
    size = _size(axis)
    if x.ndim < 1 or x.shape[0] != size:
        raise ValueError(
            f"scatter requires input shape (size, ...) = ({size}, ...) on "
            f"every rank (only root's values are read), got {x.shape}"
        )
    # all_to_all row j of the result holds rank j's chunk addressed to us;
    # row `root` is therefore exactly MPI_Scatter's result.  One collective,
    # O(|x|) traffic per rank — cheaper than bcast-then-slice (2·|x|).
    x = as_varying(x, axis)
    return lax.all_to_all(x, axis, split_axis=0, concat_axis=0)[root]


def scan(x, op: ReduceOp, axis):
    """Inclusive prefix reduction across ranks (MPI_Scan).

    Hillis–Steele over ``ppermute``: log2(size) shift-and-combine steps, each
    one ICI hop of the full buffer.  Ranks below the shift distance keep
    their partial (ppermute delivers zeros to ranks with no source; the mask
    keeps identity-correctness for non-SUM ops).
    """
    op.check_dtype(x.dtype)
    size = _size(axis)
    r = _rank(axis)
    acc = as_varying(x, axis)
    shift = 1
    while shift < size:
        shifted = lax.ppermute(
            acc, axis, [(i, i + shift) for i in range(size - shift)]
        )
        acc = jnp.where(r >= shift, op.combine(acc, shifted), acc)
        shift *= 2
    return acc.astype(x.dtype)


def sendrecv(x, perm, axis):
    """Combined send+recv along a static rank permutation (lax.ppermute).

    ``perm`` is a sequence of (source, dest) pairs — the SPMD expression of
    the reference's per-rank (source, dest) arguments
    (/root/reference/mpi4jax/_src/collective_ops/sendrecv.py:46-125).  Ranks
    not appearing as a destination receive zeros.
    """
    x = as_varying(x, axis)
    if _pallas_ring(axis):
        from . import pallas_collectives as _pc

        k = _pc.ring_shift_of(perm, _size(axis))
        if k is not None:
            return _pc.ring_shift(x, axis, k)
    return lax.ppermute(x, axis, perm)


def barrier(axis, tie=None):
    # A compiled SPMD program needs no rank barrier for correctness; this
    # returns a zero scalar that carries a genuine cross-rank data dependency
    # so callers can sequence host-visible work after it.  ``tie`` (e.g. a
    # token) is ordered before the barrier when given.
    z = as_varying(jnp.zeros((), jnp.int32), axis)
    if tie is not None:
        z = lax.optimization_barrier((z, tie))[0]
    return lax.psum(z, axis)


def ring_perm(size: int, shift: int = 1, wrap: bool = True):
    """(source, dest) pairs sending each rank's data to ``rank + shift``."""
    pairs = []
    for i in range(size):
        j = i + shift
        if wrap:
            pairs.append((i, j % size))
        elif 0 <= j < size:
            pairs.append((i, j))
    return pairs
