"""Pallas TPU flash-attention kernels for ring attention.

The reference framework has no attention kernels at all (its long-context
building block is the token-ordered ``sendrecv`` ring, reference
``mpi4jax/_src/collective_ops/sendrecv.py:46-125``); this module is the
TPU-native superset: the *local block* of ring attention is computed by a
blockwise online-softmax (flash) kernel running out of VMEM on the MXU,
while the k/v blocks travel the ring via ``lax.ppermute`` over ICI.

Design
------
* ``_flash_fwd_block`` computes one ring step's contribution for the whole
  local q against the currently-held k/v block and returns the *partial*
  ``(o_unnormalized, m, l)`` triple in float32.  The cross-step combine is
  ~10 VPU ops done in plain JAX, so the ``lax.scan`` over ring steps stays
  differentiable-shaped and XLA overlaps the ppermute with the next
  kernel launch.
* The ring is wrapped in a ``jax.custom_vjp`` at the *ring* level: the
  backward pass re-runs the ring (one extra rotation of k/v) using the
  standard flash backward identities with the saved logsumexp, computing
  dq locally and letting dk/dv ride the ring home with their blocks.
  Backward kernels (``_bwd_dq_kernel``, ``_bwd_dkv_kernel``) recompute the
  probabilities blockwise, so backward memory is O(block_q * block_k).
* Causality is resolved at *global* positions: block offsets arrive as
  scalar-prefetch operands (they are traced values inside the ring scan),
  and fully-masked (q-block, k-block) pairs are skipped with ``pl.when``.

Runs in Pallas interpret mode off-TPU so the CPU test mesh exercises the
identical code path.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..parallel.ring import _ring_shift_many as _shift_many

NEG_INF = -1e30
_TRANS_B = (((1,), (1,)), ((), ()))  # contract last dims: x @ y.T
_TRANS_A = (((0,), (0,)), ((), ()))  # contract first dims: x.T @ y

# Exp used by the forward online softmax.  Module-level so the roofline
# experiment (bench.py::bench_flash_experiments) can swap in a
# linear stand-in of the same shape/cost-class-minus-transcendental and
# measure whether fwd MFU is bound by the VPU's exp throughput (the
# r3/r4 40%-vs-14% dispute, VERDICT r4 weak #2).  Production path is
# always jnp.exp.
_EXP = jnp.exp

# Scoped-VMEM budget for the tuned kernels: the (block_q, block_k) f32
# temporaries at the 1024-block sweet spot exceed Mosaic's 16MB default;
# v5e has 128MB of VMEM per core.  Shared by the shallow-water kernel.
VMEM_LIMIT_BYTES = 100 * 1024 * 1024


def target_platform() -> str:
    """Platform the surrounding computation executes on.

    Inside ``shard_map``/``use_mesh`` tracing, the abstract mesh knows the
    actual device kind — which may differ from ``jax.default_backend()``
    (e.g. a forced-CPU debug mesh on a TPU host).  Falls back to the
    default backend outside any mesh context.
    """
    try:
        mesh = jax.sharding.get_abstract_mesh()
        kind = getattr(getattr(mesh, "abstract_device", None),
                       "device_kind", None)
        if kind:
            return "tpu" if "tpu" in str(kind).lower() else str(kind).lower()
    except Exception:
        pass
    return jax.default_backend()


def _interpret_default() -> bool:
    return target_platform() != "tpu"


def pick_block(t: int, preferred: int) -> int:
    """Largest divisor of ``t`` that is <= preferred (128-friendly first)."""
    b = min(preferred, t)
    while t % b:
        b -= 1
    return b


# ---------------------------------------------------------------------------
# forward kernel: one k/v block vs the whole local q
# ---------------------------------------------------------------------------


def _scores(q_ref, k_ref, q_start, k_start, scale, causal, block_q, block_k):
    # feed the MXU in the input dtype (bf16 x bf16 -> f32 runs at full
    # rate; upcasting first would force multi-pass f32 matmuls)
    s = lax.dot_general(q_ref[...], k_ref[...], _TRANS_B,
                        preferred_element_type=jnp.float32)
    if scale != 1.0:  # the public entry pre-scales q, making this a no-op
        s = s * scale
    if causal:
        rows = q_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
        cols = k_start + lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
        s = jnp.where(cols <= rows, s, NEG_INF)
    return s


def _fwd_kernel(off_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref,
                m_s, l_s, acc, *, scale, causal, block_q, block_k):
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc[...] = jnp.zeros_like(acc)

    q_start = off_ref[0] + pl.program_id(1) * block_q
    k_start = off_ref[1] + ik * block_k
    should_run = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(should_run)
    def _compute():
        s = _scores(q_ref, k_ref, q_start, k_start, scale, causal,
                    block_q, block_k)
        m_prev, l_prev = m_s[...], l_s[...]          # (BQ, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_next = jnp.maximum(m_prev, m_cur)
        p = _EXP(s - m_next)                         # (BQ, BK)
        alpha = _EXP(m_prev - m_next)
        m_s[...] = m_next
        l_s[...] = l_prev * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc[...] = acc[...] * alpha + lax.dot(
            p.astype(v_ref.dtype), v_ref[...],
            preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _store():
        o_ref[...] = acc[...]
        m_ref[...] = m_s[...]
        l_ref[...] = l_s[...]


def _kv_index(causal, block_q, block_k, nk):
    if not causal:
        return lambda b, i, j, *_: (b, j, 0)
    return _causal_kv_index(block_q, block_k, nk)


def _q_index(causal, block_q, block_k, nq):
    if not causal:
        return lambda b, j, i, *_: (b, i, 0)
    return _causal_q_index(block_q, block_k, nq)


def _causal_kv_index(block_q, block_k, nk):
    """k/v BlockSpec index map that CLAMPS fully-masked k blocks to the
    row's last valid block.  ``pl.when`` skips the compute of masked
    (q, k) pairs, but the grid pipeline still fetches their k/v blocks —
    measured on the v5e: causal fwd ran at the same wall time as
    non-causal (2x the flops), i.e. half the programs were pure fetch
    overhead.  Mapping a skipped program to the block already resident
    makes Mosaic elide the DMA (same-index revisit), so masked programs
    cost ~nothing.  Offsets are the scalar-prefetch operand, so the
    clamp is correct at every ring step (rows entirely in the future
    clamp to block 0 and the whole row is skipped)."""

    def index(b, i, j, offs):
        jmax = (offs[0] - offs[1] + (i + 1) * block_q - 1) // block_k
        return (b, jnp.clip(jnp.minimum(j, jmax), 0, nk - 1), 0)

    return index


def _causal_q_index(block_q, block_k, nq):
    """q-side analog for the k-major dkv grid: clamp not-yet-valid q
    blocks up to the k block's first valid q row (see _causal_kv_index).
    """

    def index(b, j, i, offs):
        imin = (offs[1] - offs[0] + j * block_k) // block_q
        return (b, jnp.clip(jnp.maximum(i, imin), 0, nq - 1), 0)

    return index


def _flash_fwd_block(q, k, v, q_off, k_off, *, scale, causal,
                     block_q, block_k, interpret):
    """Partial flash attention of local q against one k/v ring block.

    q: (BH, Tq, D); k, v: (BH, Tk, D); offsets are traced global starts.
    Returns float32 (o_unnormalized (BH,Tq,D), m (BH,Tq,1), l (BH,Tq,1)).
    """
    bh, tq, d = q.shape
    tk = k.shape[1]
    nq, nk = tq // block_q, tk // block_k
    offs = jnp.stack([q_off, k_off]).astype(jnp.int32)

    kv_idx = _kv_index(causal, block_q, block_k, nk)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((None, block_k, d), kv_idx),
            pl.BlockSpec((None, block_k, d), kv_idx),
        ],
        out_specs=[
            pl.BlockSpec((None, block_q, d), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j, *_: (b, i, 0)),
            pl.BlockSpec((None, block_q, 1), lambda b, i, j, *_: (b, i, 0)),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
    )
    return pl.pallas_call(
        partial(_fwd_kernel, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k),
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), jnp.float32),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=VMEM_LIMIT_BYTES),
        interpret=interpret,
    )(offs, q, k, v)


# ---------------------------------------------------------------------------
# backward kernels (standard flash identities with saved logsumexp)
# ---------------------------------------------------------------------------


def _bwd_dq_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
                   dq_ref, dq_acc, *, scale, causal, block_q, block_k):
    ik = pl.program_id(2)
    nk = pl.num_programs(2)

    @pl.when(ik == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)

    q_start = off_ref[0] + pl.program_id(1) * block_q
    k_start = off_ref[1] + ik * block_k
    should_run = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(should_run)
    def _compute():
        s = _scores(q_ref, k_ref, q_start, k_start, scale, causal,
                    block_q, block_k)
        p = jnp.exp(s - lse_ref[...])                        # (BQ, BK)
        dp = lax.dot_general(do_ref[...], v_ref[...], _TRANS_B,
                             preferred_element_type=jnp.float32)
        ds = p * (dp - dlt_ref[...])
        if scale != 1.0:
            ds = ds * scale
        dq_acc[...] += lax.dot(ds.astype(k_ref.dtype), k_ref[...],
                               preferred_element_type=jnp.float32)

    @pl.when(ik == nk - 1)
    def _store():
        dq_ref[...] = dq_acc[...]


def _bwd_dkv_kernel(off_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *,
                    scale, causal, block_q, block_k):
    iq = pl.program_id(2)
    nq = pl.num_programs(2)

    @pl.when(iq == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    q_start = off_ref[0] + iq * block_q
    k_start = off_ref[1] + pl.program_id(1) * block_k
    should_run = (q_start + block_q - 1 >= k_start) if causal else True

    @pl.when(should_run)
    def _compute():
        s = _scores(q_ref, k_ref, q_start, k_start, scale, causal,
                    block_q, block_k)
        p = jnp.exp(s - lse_ref[...])
        dv_acc[...] += lax.dot_general(p.astype(do_ref.dtype), do_ref[...],
                                       _TRANS_A,
                                       preferred_element_type=jnp.float32)
        dp = lax.dot_general(do_ref[...], v_ref[...], _TRANS_B,
                             preferred_element_type=jnp.float32)
        ds = p * (dp - dlt_ref[...])
        if scale != 1.0:
            ds = ds * scale
        dk_acc[...] += lax.dot_general(ds.astype(q_ref.dtype), q_ref[...],
                                       _TRANS_A,
                                       preferred_element_type=jnp.float32)

    @pl.when(iq == nq - 1)
    def _store():
        dk_ref[...] = dk_acc[...]
        dv_ref[...] = dv_acc[...]


def _flash_bwd_block(q, k, v, do, lse, delta, q_off, k_off, *,
                     scale, causal, block_q, block_k, interpret):
    """One ring step of the backward pass: (dq, dk, dv) in float32."""
    bh, tq, d = q.shape
    tk = k.shape[1]
    nq, nk = tq // block_q, tk // block_k
    offs = jnp.stack([q_off, k_off]).astype(jnp.int32)

    q_spec = pl.BlockSpec((None, block_q, d), lambda b, i, j, *_: (b, i, 0))
    r_spec = pl.BlockSpec((None, block_q, 1), lambda b, i, j, *_: (b, i, 0))
    kv_idx = _kv_index(causal, block_q, block_k, nk)
    k_spec = pl.BlockSpec((None, block_k, d), kv_idx)

    dq = pl.pallas_call(
        partial(_bwd_dq_kernel, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, nq, nk),
            in_specs=[q_spec, k_spec, k_spec, q_spec, r_spec, r_spec],
            out_specs=[q_spec],
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((bh, tq, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            # (block_q, block_k) f32 temporaries (s/p/dp/ds) blow the
            # 16MB default scoped-vmem cap at the tuned 1024 blocks
            vmem_limit_bytes=VMEM_LIMIT_BYTES),
        interpret=interpret,
    )(offs, q, k, v, do, lse, delta)[0]

    # k-block-major grid: q tiles innermost so dk/dv accumulate in scratch
    qi_idx = _q_index(causal, block_q, block_k, nq)
    qi_spec = pl.BlockSpec((None, block_q, d), qi_idx)
    ri_spec = pl.BlockSpec((None, block_q, 1), qi_idx)
    kj_spec = pl.BlockSpec((None, block_k, d), lambda b, j, i, *_: (b, j, 0))
    dk, dv = pl.pallas_call(
        partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                block_q=block_q, block_k=block_k),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(bh, nk, nq),
            in_specs=[qi_spec, kj_spec, kj_spec, qi_spec, ri_spec, ri_spec],
            out_specs=[kj_spec, kj_spec],
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)],
        ),
        out_shape=[jax.ShapeDtypeStruct((bh, tk, d), jnp.float32),
                   jax.ShapeDtypeStruct((bh, tk, d), jnp.float32)],
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=VMEM_LIMIT_BYTES),
        interpret=interpret,
    )(offs, q, k, v, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------------------------
# ring orchestration (custom VJP at the ring level)
# ---------------------------------------------------------------------------


def _to_bhtd(x):
    b, t, h, d = x.shape
    return x.transpose(0, 2, 1, 3).reshape(b * h, t, d)


def _from_bhtd(x, b, h):
    bh, t, d = x.shape
    return x.reshape(b, h, t, d).transpose(0, 2, 1, 3)


def _ring_forward(q, k, v, axis, causal, scale, block_q, block_k, interpret):
    size = lax.axis_size(axis)
    my = lax.axis_index(axis)
    b, t, h, d = q.shape
    qf, kf, vf = _to_bhtd(q), _to_bhtd(k), _to_bhtd(v)
    q_off = my * t

    def step(carry, i):
        o, m, l, k_cur, v_cur = carry
        src = (my - i) % size
        o_b, m_b, l_b = _flash_fwd_block(
            qf, k_cur, v_cur, q_off, src * t, scale=scale, causal=causal,
            block_q=block_q, block_k=block_k, interpret=interpret)
        m_new = jnp.maximum(m, m_b)
        a, a_b = jnp.exp(m - m_new), jnp.exp(m_b - m_new)
        o = o * a + o_b * a_b
        l = l * a + l_b * a_b
        return (o, m_new, l) + _shift_many((k_cur, v_cur), axis), None

    from ._mesh_impl import as_varying

    o0 = as_varying(jnp.zeros((b * h, t, d), jnp.float32), axis)
    m0 = as_varying(jnp.full((b * h, t, 1), NEG_INF, jnp.float32), axis)
    l0 = as_varying(jnp.zeros((b * h, t, 1), jnp.float32), axis)
    (o, m, l, _, _), _ = lax.scan(step, (o0, m0, l0, kf, vf),
                                  jnp.arange(size))
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = (o / l_safe).astype(q.dtype)
    lse = m + jnp.log(l_safe)
    return _from_bhtd(out, b, h), lse


@partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _ring_flash(q, k, v, axis, causal, scale, block_q, block_k, interpret):
    out, _ = _ring_forward(q, k, v, axis, causal, scale,
                           block_q, block_k, interpret)
    return out


def _ring_flash_fwd(q, k, v, axis, causal, scale, block_q, block_k,
                    interpret):
    out, lse = _ring_forward(q, k, v, axis, causal, scale,
                             block_q, block_k, interpret)
    return out, (q, k, v, out, lse)


def _ring_flash_bwd(axis, causal, scale, block_q, block_k, interpret,
                    res, g):
    q, k, v, out, lse = res
    size = lax.axis_size(axis)
    my = lax.axis_index(axis)
    b, t, h, d = q.shape
    qf, kf, vf = _to_bhtd(q), _to_bhtd(k), _to_bhtd(v)
    dof = _to_bhtd(g)  # keep cotangent in its own dtype for bf16 MXU dots
    outf = _to_bhtd(out).astype(jnp.float32)
    delta = jnp.sum(dof.astype(jnp.float32) * outf, axis=-1, keepdims=True)
    q_off = my * t

    def step(carry, i):
        dq, dk_cur, dv_cur, k_cur, v_cur = carry
        src = (my - i) % size
        dq_b, dk_b, dv_b = _flash_bwd_block(
            qf, k_cur, v_cur, dof, lse, delta, q_off, src * t,
            scale=scale, causal=causal, block_q=block_q, block_k=block_k,
            interpret=interpret)
        carry = (dq + dq_b, dk_cur + dk_b, dv_cur + dv_b,
                 k_cur, v_cur)
        # rotate the k/v blocks together with their accumulated grads;
        # after `size` hops they are back home
        return (carry[0],) + _shift_many(carry[1:], axis), None

    from ._mesh_impl import as_varying

    z_q = as_varying(jnp.zeros((b * h, t, d), jnp.float32), axis)
    z_k = jnp.zeros_like(z_q)
    (dq, dk, dv, _, _), _ = lax.scan(
        step, (z_q, z_k, z_k, kf, vf), jnp.arange(size))
    return (_from_bhtd(dq, b, h).astype(q.dtype),
            _from_bhtd(dk, b, h).astype(k.dtype),
            _from_bhtd(dv, b, h).astype(v.dtype))


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


def ring_flash_attention(q, k, v, *, axis, causal=False, scale=None,
                         block_q=1024, block_k=1024, interpret=None,
                         prescale_q=True):
    """Ring attention with Pallas flash kernels for the local blocks.

    Same contract as :func:`mpi4jax_tpu.parallel.ring.ring_attention`:
    q/k/v are ``(B, T_local, H, D)``, sequence sharded over mesh axis
    ``axis``; returns the exact attention output, differentiable.

    ``prescale_q=False`` keeps the per-score-block ``s * scale`` inside
    the kernels (the pre-r4 behavior) — exists so the MFU sweep can
    measure the prescale rewrite rather than assume it.
    """
    t = q.shape[1]
    if scale is None:
        scale = float(1.0 / (q.shape[-1] ** 0.5))
    bq = pick_block(t, block_q)
    bk = pick_block(t, block_k)
    if interpret is None:
        interpret = _interpret_default()
    # pre-scale q OUTSIDE the kernels: the per-score-block `s * scale`
    # was a full (block_q, block_k) VPU multiply per k block on a
    # VPU-bound forward — folding it into q costs one (T, D) multiply
    # total, and the custom_vjp boundary sees the scaled q so the
    # dq = scale * dq' chain is handled by plain autodiff outside
    scale = float(scale)
    if scale != 1.0 and prescale_q:
        q = (q * jnp.asarray(scale, q.dtype)).astype(q.dtype)
        scale = 1.0
    return _ring_flash(q, k, v, axis, bool(causal), scale,
                       bq, bk, bool(interpret))
