"""send — point-to-point send.

Reference: /root/reference/mpi4jax/_src/collective_ops/send.py (returns a
token only, :153-154).

A lone ``send`` is only meaningful when ranks run *different* programs — the
world tier (one process per rank, like the reference) supports it exactly.
In one SPMD program every rank executes every line, so an unpaired send has
no well-defined receiver call; the mesh tier rejects it with guidance toward
:func:`mpi4jax_tpu.sendrecv` (ppermute), which expresses the same data
motion deadlock-free.
"""

from __future__ import annotations

from ..utils import validation as _validation
from . import _dispatch


def send(x, dest, tag=0, *, comm=None, token=None):
    """Send ``x`` to rank ``dest`` (world tier only; see module docstring)."""
    x = _validation.check_array("x", x)
    dest = _validation.check_static_int("dest", dest)
    tag = _validation.check_static_int("tag", tag)
    comm = _dispatch.resolve_comm(comm)

    if _dispatch.is_mesh(comm):
        raise NotImplementedError(
            "send() has no meaning inside a single SPMD program: every rank "
            "executes the same code, so there is no separate receiver. Use "
            "sendrecv(x, perm=...) / sendrecv(x, shift=...) (compiled to "
            "lax.ppermute over ICI), or run one process per rank via "
            "`python -m mpi4jax_tpu.runtime.launch` for MPMD send/recv."
        )

    from . import _world_impl

    _validation.check_in_range("dest", dest, comm.size(),
                               op="send", comm=comm)
    _validation.check_wire_dtype("send", x, comm)
    return _world_impl.send(x, dest, tag, comm, token)
