from .allgather import allgather
from .allreduce import allreduce
from .alltoall import alltoall
from .barrier import barrier
from .bcast import bcast
from .gather import gather
from .recv import recv
from .reduce import reduce
from .reduce_ops import (
    ALL_OPS,
    BAND,
    BOR,
    BXOR,
    LAND,
    LOR,
    LXOR,
    MAX,
    MIN,
    PROD,
    SUM,
    ReduceOp,
    as_reduce_op,
    custom_op,
)
from .scan import scan
from .scatter import scatter
from .send import send
from .neighbor import neighbor_exchange
from .sendrecv import permute, sendrecv
from ._dispatch import create_token

__all__ = [
    "allgather",
    "allreduce",
    "alltoall",
    "barrier",
    "bcast",
    "create_token",
    "gather",
    "permute",
    "neighbor_exchange",
    "recv",
    "reduce",
    "scan",
    "scatter",
    "send",
    "sendrecv",
    "ReduceOp",
    "as_reduce_op",
    "custom_op",
    "ALL_OPS",
    "SUM",
    "PROD",
    "MAX",
    "MIN",
    "LAND",
    "LOR",
    "LXOR",
    "BAND",
    "BOR",
    "BXOR",
]
