"""scan — inclusive prefix reduction across ranks (MPI_Scan).

Reference: /root/reference/mpi4jax/_src/collective_ops/scan.py (same-shape
inclusive scan, :163-167).  Mesh tier: a log2(size) Hillis–Steele ladder of
``lax.ppermute`` steps (ops/_mesh_impl.py:scan) — each step one ICI hop, no
host round-trips.
"""

from __future__ import annotations


from ..utils import validation as _validation
from . import _dispatch, _mesh_impl
from .reduce_ops import SUM, as_reduce_op


def scan(x, op=SUM, *, comm=None, token=None):
    """Rank r receives ``op(x_0, ..., x_r)`` (inclusive prefix)."""
    op = as_reduce_op(op)
    x = _validation.check_array("x", x)
    comm = _dispatch.resolve_comm(comm)

    if _dispatch.is_mesh(comm):
        body = lambda v: _mesh_impl.scan(v, op, comm.axis)
    else:
        from . import _world_impl

        _validation.check_reduce_dtype("scan", op, x, comm)
        _validation.check_wire_dtype("scan", x, comm)
        body = lambda v: _world_impl.scan(v, op, comm)
        if op.custom:  # allgather + local prefix fold, token-chained
            return _dispatch.maybe_tokenized(
                body, x, token,
                token_fn=_world_impl.custom_fold_token_fn(op, comm,
                                                          prefix=True))
        return _dispatch.maybe_tokenized(
            body, x, token,
            token_fn=_world_impl.token_variant_fn("scan", comm=comm,
                                                  op=op))
    return _dispatch.maybe_tokenized(body, x, token)
