"""alltoall — transpose chunks across ranks (the FFT-slab / Ulysses move).

Reference: /root/reference/mpi4jax/_src/collective_ops/alltoall.py (leading
axis must equal nproc :71-73,99-101).  Mesh tier is a single
``lax.all_to_all`` — on TPU this is the bisection-bandwidth collective that
sequence-parallel attention (parallel/ulysses.py) and spectral transposes
ride.
"""

from __future__ import annotations

from ..utils import validation as _validation
from . import _dispatch, _mesh_impl


def alltoall(x, *, comm=None, token=None):
    """Exchange chunks: output row ``j`` is rank ``j``'s input row ``rank``.

    ``x`` must have shape ``(size, ...)`` on every rank.
    """
    x = _validation.check_array("x", x)
    comm = _dispatch.resolve_comm(comm)

    if _dispatch.is_mesh(comm):
        body = lambda v: _mesh_impl.alltoall(v, comm.axis)
    else:
        from . import _world_impl

        _validation.check_wire_dtype("alltoall", x, comm)
        body = lambda v: _world_impl.alltoall(v, comm)
        if x.ndim < 1 or x.shape[0] != comm.size():
            _validation.fail(
                f"alltoall requires leading axis == communicator size "
                f"({comm.size()})",
                op="alltoall", comm=comm, x=x, exc=ValueError)
        return _dispatch.maybe_tokenized(
            body, x, token,
            token_fn=_world_impl.token_variant_fn("alltoall", comm=comm))
    return _dispatch.maybe_tokenized(body, x, token)
