"""alltoall — transpose chunks across ranks (the FFT-slab / Ulysses move).

Reference: /root/reference/mpi4jax/_src/collective_ops/alltoall.py (leading
axis must equal nproc :71-73,99-101).  Mesh tier is a single
``lax.all_to_all`` — on TPU this is the bisection-bandwidth collective that
sequence-parallel attention (parallel/ulysses.py) and spectral transposes
ride.
"""

from __future__ import annotations

from ..utils import validation as _validation
from . import _dispatch, _mesh_impl


def alltoall(x, *, comm=None, token=None, compression=None, algo=None):
    """Exchange chunks: output row ``j`` is rank ``j``'s input row ``rank``.

    ``x`` must have shape ``(size, ...)`` on every rank.

    Args:
        x: array of shape ``(size, ...)``.
        comm: communicator (default: ambient).
        token: optional ordering token; if given, returns ``(result,
            token)``.
        compression: ``"int8"`` for the bandwidth-saving quantized wire
            format on a world comm (real floating dtypes, ~1e-2
            relative error on off-rank chunks; the own-rank chunk stays
            exact).  Degrades to the exact exchange — consistently on
            every rank — when the native quantized engine is absent or
            ``MPI4JAX_TPU_COLL_QUANT=deny``.
        algo: force an alltoall schedule for THIS call on a world comm
            (``"ring"``/``"qalltoall"``/``"halltoall"``/
            ``"hqalltoall"``) instead of the engine's selection.  Every
            rank must force the same one; ineligible picks degrade
            exactly like table rows (``mpi4jax_tpu.tune``), and the
            schedule signature stays plain ``alltoall`` — forcing is
            invisible to the static verifier.
    """
    x = _validation.check_array("x", x)
    comm = _dispatch.resolve_comm(comm)

    if algo is not None:
        from .. import tune

        algo = tune._check_algo(algo, "alltoall")
        if _dispatch.is_mesh(comm):
            _validation.fail(
                "algo= forces a WORLD-tier transport schedule; the mesh "
                "tier compiles to one XLA collective",
                op="alltoall", comm=comm, x=x, exc=NotImplementedError)
        if compression is not None:
            _validation.fail(
                "compression='int8' selects its own wire format; do not "
                "combine it with algo=",
                op="alltoall", comm=comm, x=x, exc=ValueError)

    if compression is not None:
        if compression != "int8":
            _validation.fail(
                f"unknown compression {compression!r}; supported: 'int8'",
                op="alltoall", comm=comm, x=x, exc=ValueError)
        if _dispatch.is_mesh(comm):
            _validation.fail(
                "compression='int8' rides the world-tier transport wire "
                "format; the mesh tier compiles to one XLA collective",
                op="alltoall", comm=comm, x=x, exc=NotImplementedError)
        from .quantized import check_quantizable, native_quant_alltoall

        check_quantizable(x, comm)
        # None -> exact exchange (pre-quant native library, or
        # COLL_QUANT=deny) — the same process-wide signals on every
        # rank, so the degrade is rank-consistent
        algo = native_quant_alltoall(comm)

    if _dispatch.is_mesh(comm):
        body = lambda v: _mesh_impl.alltoall(v, comm.axis)
    else:
        from . import _world_impl

        _validation.check_wire_dtype("alltoall", x, comm)
        body = lambda v: _world_impl.alltoall(v, comm, algo=algo)
        if x.ndim < 1 or x.shape[0] != comm.size():
            _validation.fail(
                f"alltoall requires leading axis == communicator size "
                f"({comm.size()})",
                op="alltoall", comm=comm, x=x, exc=ValueError)
        return _dispatch.maybe_tokenized(
            body, x, token,
            token_fn=_world_impl.token_variant_fn("alltoall", comm=comm,
                                                  algo=algo))
    return _dispatch.maybe_tokenized(body, x, token)
