"""bcast — broadcast the root's array to every rank.

Reference: /root/reference/mpi4jax/_src/collective_ops/bcast.py (root passes
its ``x`` through, other ranks receive root's data, :76-81,180-192; the
rank-dependent dummy-output trick there is a per-process-compilation artifact
that SPMD does not need).  Mesh tier: a masked ``lax.psum`` — only the root's
shard contributes, one fused ICI collective.
"""

from __future__ import annotations

from ..utils import validation as _validation
from . import _dispatch, _mesh_impl


def bcast(x, root=0, *, comm=None, token=None):
    """Every rank receives root's ``x``; all ranks must pass the same shape."""
    x = _validation.check_array("x", x)
    root = _validation.check_static_int("root", root)
    comm = _dispatch.resolve_comm(comm)

    if _dispatch.is_mesh(comm):
        body = lambda v: _mesh_impl.bcast(v, root, comm.axis)
    else:
        from . import _world_impl

        _validation.check_in_range("root", root, comm.size(),
                                   op="bcast", comm=comm)
        _validation.check_wire_dtype("bcast", x, comm)
        body = lambda v: _world_impl.bcast(v, root, comm)
        return _dispatch.maybe_tokenized(
            body, x, token,
            token_fn=_world_impl.token_variant_fn("bcast", comm=comm,
                                                  root=root))
    return _dispatch.maybe_tokenized(body, x, token)
