"""Comm resolution, tier dispatch, and token threading shared by all ops.

The reference threads an explicit XLA token through every op
(/root/reference/mpi4jax/_src/collective_ops/allreduce.py:63-64,101-104); its
experimental notoken layer uses ordered effects instead (SURVEY.md §2.2).
Here the *primary* API is tokenless:

- mesh tier: ordering holds by SPMD construction (one program, one order);
- world tier: primitives carry an ordered effect, the compiler threads the
  runtime token.

The ``token=`` kwarg is still accepted on every op for migration and for
expressing extra ordering constraints the dataflow doesn't: tokens are plain
scalar arrays tied to op inputs/outputs with ``lax.optimization_barrier``.
"""

from __future__ import annotations

import jax.numpy as jnp
from jax import lax

from ..parallel.mesh import MeshComm, get_default_comm
from ..runtime.transport import WorldComm


def resolve_comm(comm):
    if comm is None:
        comm = get_default_comm()
    if not isinstance(comm, (MeshComm, WorldComm)):
        raise TypeError(
            f"comm must be a mpi4jax_tpu communicator (MeshComm or "
            f"WorldComm), got {type(comm).__qualname__}"
        )
    return comm


def is_mesh(comm) -> bool:
    return isinstance(comm, MeshComm)


def create_token(x=None):
    """A fresh ordering token (a zero scalar; tied to ``x`` if given)."""
    from . import _world_impl

    token = jnp.zeros((), jnp.uint32)
    if x is not None:
        token, _ = lax.optimization_barrier((token, x))
        # a data-tied token legitimately roots a NEW chain (ordering
        # rides the dataflow) — exempt it from the explicit-mode
        # unthreaded-chain guard
        _world_impl._chain_guard.note_rooted(token)
    else:
        # a BARE fresh token mid-chain is the classic footgun — the
        # guard flags exactly these (known-fresh), never tokens it
        # merely hasn't seen
        _world_impl._chain_guard.note_fresh(token)
    return token


def token_in(token, *arrays):
    """Make ``arrays`` depend on ``token`` (ops wait for the token)."""
    if token is None:
        return arrays if len(arrays) != 1 else arrays[0]
    tied = lax.optimization_barrier((token, *arrays))[1:]
    return tied if len(tied) != 1 else tied[0]


def token_out(token, *results):
    """A new token that carries a dependency on ``results``."""
    if token is None:
        token = jnp.zeros((), jnp.uint32)
    return lax.optimization_barrier((token, *results))[0]


def maybe_tokenized(fn, x, token, token_fn=None):
    """Run op body ``fn(x)`` with optional token threading.

    Returns ``fn(x)`` when ``token is None`` (primary API), else
    ``(fn(x'), token')`` with the token tied through the op.

    ``token_fn(x, token) -> (result, token')`` is the world tier's
    token-OPERAND route, used in explicit-token (unordered-effect) mode:
    XLA folds ``optimization_barrier`` value ties around opaque custom
    calls, so there the token must ride through the call itself as a
    real operand/result (the reference's L1 wire format,
    allreduce.py:101-104 there).
    """
    if token is None:
        if token_fn is not None:
            from . import _world_impl

            if not _world_impl._ordered_now():
                # a tokenless world op inside explicit mode orders
                # against NOTHING — flag it when chains are live
                _world_impl._chain_guard.note_unthreaded(
                    getattr(token_fn, "comm", None))
        return fn(x)
    if token_fn is not None:
        from . import _world_impl

        if not _world_impl._ordered_now():
            return token_fn(x, token)
    x = token_in(token, x)
    result = fn(x)
    return result, token_out(token, result)
