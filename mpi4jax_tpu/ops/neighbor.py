"""neighbor_exchange — bidirectional 1-D ring exchange in one op.

The MPI_Neighbor_alltoall analog on a ring segment, and the halo-
exchange hot path of the world tier: both direction strips move in a
single deadlock-free operation (async sends posted before either
receive).  No reference counterpart — its shallow-water demo issues up
to four token-ordered sendrecv/send/recv calls per boundary pass
(/root/reference/examples/shallow_water.py:173-271); this op is the
superset primitive those four calls become.

World tier only: the mesh tier's halo path is
:func:`mpi4jax_tpu.parallel.halo.halo_exchange` (batched
``lax.ppermute`` over ICI), which already moves both directions of all
fields per axis in compiler-scheduled collectives.
"""

from __future__ import annotations

from ..utils import validation as _validation
from . import _dispatch


def neighbor_exchange(to_lo, to_hi, *, lo, hi, comm=None, tag=60,
                      token=None):
    """Exchange strips with the two 1-D ring neighbors, one op.

    Args:
        to_lo / to_hi: same-shape strips sent to the low / high
            neighbor.
        lo / hi: neighbor ranks, or ``None`` for a wall
            (``MPI_PROC_NULL`` style: nothing is sent or received on
            that side; the returned strip there is the opposite input,
            passthrough — ignore it).
        tag: base message tag (the high-direction frames use ``tag+1``).
        token: optional explicit ordering token; with a token the
            return is ``((from_lo, from_hi), token)``.

    Returns:
        ``(from_lo, from_hi)``: the strip received from the low / high
        neighbor.  Self-wrap (both neighbors == own rank, a periodic
        ring of one) is a local rotation.  Deadlock-free for any
        chain/ring when every member calls at the same program
        position.
    """
    to_lo = _validation.check_array("to_lo", to_lo)
    to_hi = _validation.check_array("to_hi", to_hi)
    comm = _dispatch.resolve_comm(comm)
    if _dispatch.is_mesh(comm):
        raise NotImplementedError(
            "neighbor_exchange is a world-tier op; on the mesh tier use "
            "mpi4jax_tpu.parallel.halo.halo_exchange (batched ppermute "
            "over ICI) or sendrecv(shift=±1)"
        )
    from . import _world_impl

    for name, r in (("lo", lo), ("hi", hi)):
        if r is not None:
            _validation.check_in_range(name, int(r), comm.size())
    return _world_impl.neighbor_exchange(
        to_lo, to_hi, lo=lo, hi=hi, comm=comm, tag=tag, token=token
    )
