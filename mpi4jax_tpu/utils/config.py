"""Environment-variable configuration registry.

The reference reads env vars ad hoc with a truthiness parser duplicated in two
places (see SURVEY.md §5.6, citing /root/reference/mpi4jax/_src/decorators.py:19-24
and xla_bridge/__init__.py:18-19).  Here every knob is declared once, in one
table, with one parser.

Knobs (all prefixed ``MPI4JAX_TPU_``):

- ``MPI4JAX_TPU_DEBUG``       — per-call debug tracing (rank | call-id | op | dt).
- ``MPI4JAX_TPU_NO_WARN_JAX_VERSION`` — silence the jax version check.
- ``MPI4JAX_TPU_DISABLE_FFI`` — skip the native XLA FFI custom-call fast
                                path on cpu and route world-tier ops through
                                host callbacks instead (debug aid).
- ``MPI4JAX_TPU_DISABLE_SHM`` — force TCP collectives even when every rank
                                of a communicator shares one host (the shm
                                arena is the default there; read natively in
                                native/tpucomm.cc).
- ``MPI4JAX_TPU_SHM_MB``      — shm arena slot size in MB (default 32; read
                                natively).
- ``MPI4JAX_TPU_SHM_RING_KB`` — per-directed-pair p2p ring size in KB
                                (default 1024; read natively).  Messages
                                <= ring/4 travel inline; larger ones
                                leave an ordering stub and ride TCP.
- ``MPI4JAX_TPU_DISABLE_SHM_P2P`` — keep point-to-point on TCP while
                                collectives stay on the shm arena (CI
                                axis; must agree across ranks, read
                                natively).
- ``MPI4JAX_TPU_STRICT_TOKENS`` — explicit-token chain guard: unset =
                                warn on an unthreaded/forked world-op
                                token chain at trace time, 1 = raise,
                                0 = silent (ops/_world_impl.py).
- ``MPI4JAX_TPU_STAGED_EAGER`` — force (1) or forbid (0) staged-eager
                                dispatch for eager world ops on
                                callback-less backends; default
                                auto-detects the axon tunnel
                                (ops/_world_impl.py).
- ``MPI4JAX_TPU_RANK`` / ``MPI4JAX_TPU_SIZE`` / ``MPI4JAX_TPU_COORD`` /
  ``MPI4JAX_TPU_HOSTS`` — world job description (rank, world size,
                                rendezvous host:base-port, per-rank
                                host table); set by the launcher,
                                adopted from mpirun/srun/PMI env when
                                absent (runtime/transport.py).
- ``MPI4JAX_TPU_HOST``        — this rank's reachable address for
                                ``WorldComm.from_mpi`` bootstrap
                                (default 127.0.0.1).
- ``MPI4JAX_TPU_SHM_TIMEOUT_S`` — shm barrier timeout seconds (default 180;
                                read natively).  Capped by
                                ``MPI4JAX_TPU_TIMEOUT_S`` when that is
                                smaller.
- ``MPI4JAX_TPU_TIMEOUT_S``   — progress-based deadline (seconds) on every
                                blocking transport wait: send/recv/
                                ANY_SOURCE polls, collective frames, and
                                (as a cap) shm barrier/ring waits.  The
                                clock resets whenever any byte moves, so
                                slow-but-live bulk transfers survive
                                while a hung peer trips the deadline
                                with a diagnostic naming the op, the
                                peer, the comm, and the bytes moved.
                                Default 0 = no deadline (historic
                                behavior; read natively).
- ``MPI4JAX_TPU_CONNECT_TIMEOUT_S`` — bootstrap deadline (seconds) for
                                dialing lower ranks (exponential
                                backoff, last errno reported; default
                                30, matching the old fixed spin) AND
                                for the accept side waiting on higher
                                ranks (bounded by default since the
                                self-healing PR; 0 = explicitly
                                unbounded on both sides; read
                                natively).
- ``MPI4JAX_TPU_LAUNCH_GRACE_S`` — launcher teardown grace period
                                (seconds, default 5) between escalation
                                steps (SIGINT/SIGTERM -> SIGKILL) when
                                reaping a rank group
                                (runtime/launch.py).
- ``MPI4JAX_TPU_TEST_TIMEOUT_S`` — per-test hard deadline for the
                                world-tier suite (seconds, default 600;
                                0 disables), enforced by
                                tests/world/conftest.py via SIGALRM so
                                a hung multi-process job fails its own
                                test instead of the suite's global
                                wall clock.
- ``MPI4JAX_TPU_FAULT``       — deterministic fault injection in the
                                native transport, for exercising the
                                failure-detection paths:
                                ``rank=R,point=send|recv|connect,
                                after=N,action=hang|exit|close``.  On
                                rank R the (N+1)-th op at `point` hangs
                                forever, exits with code 17 (simulated
                                crash), or shuts down every mesh socket
                                (simulated partition).  The self-healing
                                chaos actions: ``action=reset`` closes
                                the op's link with SO_LINGER(0) (an RST
                                on the wire, the classic transient),
                                ``action=drop`` with ``bytes=N`` kills
                                the link mid-frame after writing N
                                bytes (default 20: inside the header),
                                ``action=delay`` with ``ms=T`` stalls
                                the op T milliseconds (default 100),
                                and ``action=corrupt`` flips one header
                                byte on the next frame (detected by the
                                wire CRC).  All four are one-shot and,
                                with ``MPI4JAX_TPU_RETRY`` unset,
                                degrade to a plain link reset.  A
                                malformed spec aborts the job (read
                                natively).
- ``MPI4JAX_TPU_RETRY``       — self-healing link retry budget: the
                                number of reconnect attempts allowed
                                per link failure before the link is
                                declared DEAD and escalates through the
                                poison -> abort -> elastic ladder.
                                Default 0 = the self-healing layer is
                                fully disarmed and every wire byte is
                                bit-identical to the historic transport
                                (frames gain a seq/epoch/CRC extended
                                header only when armed; read natively).
- ``MPI4JAX_TPU_RETRY_BACKOFF_MS`` — base reconnect backoff in
                                milliseconds (default 100).  Attempt
                                k>1 sleeps base * 2^(k-1) with 25 %
                                deterministic jitter, capped at 5 s;
                                attempt 1 dials immediately (read
                                natively).
- ``MPI4JAX_TPU_HEARTBEAT_S`` — idle-link heartbeat period in seconds
                                (default 0 = off).  The progress thread
                                pings links quiet for a full period and
                                starts recovery on links quiet for
                                three (half-open peer detection without
                                traffic; requires the progress thread;
                                read natively).
- ``MPI4JAX_TPU_WIRE_CRC``    — CRC32C on frame/control headers:
                                ``auto`` (default: on exactly when
                                ``MPI4JAX_TPU_RETRY`` arms the extended
                                header), ``0`` = off, ``1`` = require
                                (loud exit when the retry layer is
                                disarmed, since the unarmed wire has no
                                CRC slot; read natively).  Payload
                                bytes are NOT covered — docs/
                                sharp-bits.md § Self-healing links.
- ``MPI4JAX_TPU_RETRY_REPLAY_SLACK`` — test-only protocol exerciser:
                                replay N extra already-delivered frames
                                on every reconnect so the receiver's
                                seq dedup provably fires (dup counters
                                move, digests stay bit-identical;
                                read natively).
- ``MPI4JAX_TPU_JOBID``       — unique token for /dev/shm segment names
                                (the launcher sets a uuid per job; read
                                natively).
- ``MPI4JAX_TPU_COLL_ALGO``   — force world-tier TCP collective algorithms:
                                a bare name (``ring``/``rd``/``tree``)
                                forces every op, ``allreduce=ring,
                                allgather=tree`` forces per op.  Strongest
                                layer of the selection engine
                                (``mpi4jax_tpu/tune``); must agree across
                                ranks.  The same-host shm arena still wins
                                when active.
- ``MPI4JAX_TPU_COLL_QUANT``  — gate over the quantized (int8) collective
                                wire formats (``qring``/``qrd``, read
                                natively and by the ops layer):
                                ``allow`` (default) lets the decision
                                table / env / API select them and the
                                ``compression="int8"`` allreduce route
                                natively; ``deny`` degrades every
                                quantized pick to its exact twin (ring/
                                rd) and keeps compression on the Python
                                schedule — a numerics kill-switch that
                                never changes which frames match, only
                                their contents; ``force`` upgrades every
                                eligible (real floating dtype, SUM)
                                allreduce — and every eligible-dtype
                                alltoall — to the quantized twin of its
                                selected algorithm.  alltoall rides the
                                same gate: ``qalltoall``/``hqalltoall``
                                (the MoE dispatch wire) degrade under
                                ``deny`` to their exact twins, and
                                codec-ineligible dtypes (ints) always
                                run the exact exchange, consistently on
                                every rank.  Must agree across ranks
                                (frame sizes differ between exact and
                                quantized schedules; a divergent gate
                                fails fast on the size check).
- ``MPI4JAX_TPU_TUNE_CACHE``  — full path of the persistent autotune cache
                                (default ``~/.cache/mpi4jax_tpu/
                                tune_<world_size>.json``), written by
                                ``python -m mpi4jax_tpu.tune`` and loaded
                                at communicator creation.
- ``MPI4JAX_TPU_TUNE_MODEL``  — full path of the persistent cost-model
                                file (default ``~/.cache/mpi4jax_tpu/
                                model_<world_size>[_<topohash>].json``),
                                written by ``python -m mpi4jax_tpu.tune
                                --joint`` and consulted by the schedule
                                compiler when choosing gradient-bucket
                                sizes and concurrency-group caps
                                (docs/usage.md § Joint tuning).  The
                                compiler only probes the disk when this
                                knob is set — golden plans compiled
                                without it stay byte-stable.
- ``MPI4JAX_TPU_ANALYZE_TIMEOUT_S`` — wall-clock deadline (seconds,
                                default 120; 0 = no deadline) for one
                                virtual-world run of the static
                                communication verifier (``python -m
                                mpi4jax_tpu.analyze`` /
                                ``launch --verify``); a program that
                                spins past it fails analysis with an
                                ``analysis_timeout`` finding.
- ``MPI4JAX_TPU_ANALYZE_SYMBOLIC`` — rank-symbolic schedule analysis
                                (analysis/_symbolic.py): ``auto``
                                (default — canonicalizable schedules at
                                large world sizes verify once per rank-
                                equivalence class, with sound fallback
                                to the concrete path) or ``off`` (pin
                                the historic concrete path bit-for-
                                bit).  Strict parse: anything else
                                aborts loudly — a typo'd mode must not
                                silently change which verification
                                path produced a verdict.  Verdicts are
                                byte-identical either way (the
                                differential gate in
                                tests/test_symbolic.py enforces it);
                                the knob exists for pinning and for
                                bisection.
- ``MPI4JAX_TPU_NATIVE_LIB``  — absolute path of the native transport
                                library to load instead of the built
                                ``runtime/_native/libtpucomm.so``
                                (sanitizer builds, cross-build tests;
                                runtime/bridge.py skips the staleness
                                rebuild when set).
- ``MPI4JAX_TPU_TRACE``       — arm the observability recorder and dump
                                this rank's recording to
                                ``<value>.rank<r>.json`` at exit.  The
                                launcher's ``--trace out.json`` sets it
                                and merges the parts into one
                                Perfetto-loadable Chrome trace at
                                ``out.json`` (``mpi4jax_tpu/obs``,
                                docs/observability.md).  Must agree
                                across ranks (like the shm knobs): it
                                arms a collective clock-alignment
                                handshake at communicator creation.
- ``MPI4JAX_TPU_TRACE_BUF_KB`` — event-ring size in KB (default 256;
                                72-byte slots, so 3640 events), for
                                both the native transport ring and the
                                Python span ring.  Overflow keeps the
                                newest events and counts exactly how
                                many were dropped.
- ``MPI4JAX_TPU_PROGRESS_THREAD`` — async progress engine (default on):
                                every transport op is a descriptor on a
                                per-communicator submission queue driven
                                by a dedicated progress thread — small
                                sends return immediately (payload
                                copied, buffered-send semantics), other
                                ops park on a completion futex while an
                                earlier op is still in flight, and run
                                inline when the engine is idle.  ``0``
                                restores the pre-engine inline
                                execution bit-for-bit (read natively).
- ``MPI4JAX_TPU_COALESCE_BYTES`` — sends of at most this many bytes
                                that are adjacent in posted order to
                                the same peer merge into ONE wire frame
                                (split transparently on the receive
                                side, tags and per-channel order
                                preserved).  Default 4096; 0 disables
                                coalescing (read natively; needs the
                                progress engine).
- ``MPI4JAX_TPU_QUEUE_DEPTH`` — submission-queue capacity in ops
                                (default 1024, rounded up to a power of
                                two; posting parks when full — bounded
                                memory, never unbounded buffering; read
                                natively).
- ``MPI4JAX_TPU_URING``       — io_uring submission backend under the
                                progress engine (docs/sharp-bits.md
                                § "The transport floor"; read natively,
                                strict ``auto|0|1`` parser — a typo'd
                                knob aborts loudly): one batched
                                ``io_uring_enter`` moves a whole frame
                                (or descriptor burst), a registered
                                staging pool backs small frames, and
                                sends past the kernel's buffering
                                ceiling go out as MSG_ZEROCOPY with
                                the completion consumed as a CQE.
                                ``auto`` (default) probes the kernel
                                (needs io_uring with EXT_ARG, ~5.11+);
                                ``0`` keeps the poll-driven path
                                bit-for-bit (sanitizer builds, old
                                kernels); ``1`` demands it and warns
                                loudly when the kernel cannot.  Wire
                                bytes, deadlines, poison, and fault
                                injection are identical on both paths;
                                results are bit-for-bit either way.
                                ``config.uring_mode()`` mirrors the
                                parser; the RESOLVED state (on / off /
                                unavailable + reason) is native —
                                ``bridge.uring_status()`` reports it
                                and the diag transport check prints it.
- ``MPI4JAX_TPU_PLAN``        — schedule-plan execution (the analysis
                                layer's verified comm-program rewriting,
                                docs/analysis.md § "From verifier to
                                compiler").  Unset / ``0`` = off (the
                                historic token-order execution,
                                bit-for-bit); a *path* names a plan JSON
                                emitted by ``python -m mpi4jax_tpu.analyze
                                --emit-plan`` (what ``launch --plan``
                                wires up): at communicator creation the
                                rank's verified schedule installs a plan
                                runner — hoisted receives pre-post on the
                                progress engine, large sends defer their
                                completion waits; ``1`` enables runners
                                attached through the API only.  Only
                                *proved* plans execute; a diverging op
                                stream disables the plan loudly and the
                                job continues on the historic path.
                                Implies the host-callback dispatch route
                                (the FFI fast path is skipped while a
                                plan spec is set).  Must agree across
                                ranks.
- ``MPI4JAX_TPU_PLAN_BUCKET_KB`` — gradient-bucket ceiling (KB, default
                                1024) for the schedule compiler's
                                allreduce bucket marks; when set
                                EXPLICITLY it also turns on
                                ``parallel.dp.sync_gradients``
                                bucketing: adjacent small same-op/dtype
                                gradient allreduces fuse into one
                                bucketed allreduce up to this many KB.
                                0 disables bucketing.  Must agree across
                                ranks AND with the analyzer run (it
                                changes the collective schedule; the
                                launcher exports the same environment
                                to both, so they agree by default).
- ``MPI4JAX_TPU_ELASTIC``      — elastic worlds (docs/elasticity.md): a
                                transport failure raises
                                :class:`mpi4jax_tpu.elastic.RankFailure`
                                in Python (after poisoning peers so the
                                group unblocks) instead of hard-exiting
                                the process, and ``elastic.recover()``
                                rebuilds the world over the survivors.
                                Set by ``launch --elastic``; implies the
                                host-callback dispatch route (the FFI
                                fast path bakes comm handles into
                                compiled programs, which cannot survive
                                a rebind).
- ``MPI4JAX_TPU_ELASTIC_DIR``  — coordination directory between the
                                elastic launcher and the ranks: the
                                launcher announces each new world
                                generation as ``gen_<n>.json`` (member
                                map, re-derived base port) and
                                survivors poll it from
                                ``elastic.recover()``.  Set by
                                ``launch --elastic``.
- ``MPI4JAX_TPU_ELASTIC_POLICY`` — what the elastic launcher does about
                                a dead rank: ``shrink`` (default)
                                renumbers the survivors densely into a
                                smaller world; ``respawn`` restarts the
                                dead rank's program in a fresh process
                                and rebuilds at full size.
- ``MPI4JAX_TPU_ELASTIC_GRACE_S`` — how long (seconds, default 60) a
                                surviving rank waits inside
                                ``elastic.recover()`` for the
                                launcher's next generation announcement
                                before giving up (the failure then
                                propagates and the rank exits — the
                                launcher counts it lost).
- ``MPI4JAX_TPU_GENERATION``  — the world generation this process was
                                born into (0 = the original world; the
                                elastic launcher exports it to
                                respawned children).  ``elastic``
                                tracks the live generation from there;
                                obs recordings and traces carry it.
- ``MPI4JAX_TPU_SLOT``        — a rank's original *launcher slot*
                                identity, when it differs from its
                                bootstrap rank: the elastic launcher
                                exports it to respawned children (the
                                generation maps key on slots, which
                                never renumber; ``MPI4JAX_TPU_RANK``
                                carries the dense bootstrap rank).
- ``MPI4JAX_TPU_CKPT_DIR``    — default checkpoint directory for
                                ``utils/checkpoint.py``'s sharded
                                save/restore helpers and the elastic
                                training loop (unset = the caller must
                                pass a directory explicitly).
- ``MPI4JAX_TPU_TOPO``        — topology discovery at communicator
                                creation (``mpi4jax_tpu/topo``,
                                docs/usage.md § Transport tiers and
                                topology): ``auto`` (default) runs the
                                bootstrap fingerprint allgather, derives
                                the intra-island / leader
                                sub-communicators on multi-island
                                worlds, and installs the map natively;
                                ``off`` skips discovery entirely (flat
                                transport, the pre-topology behavior).
                                Must agree across ranks (the handshake
                                is collective).
- ``MPI4JAX_TPU_FAKE_HOSTS``  — virtual host partition for topology
                                testing on one machine
                                (``r0,r1|r2,r3``: groups of world ranks
                                separated by ``|``): ranks in one group
                                share a (virtual) host — they get an
                                intra-island shm arena — while ranks in
                                different groups are treated as
                                host-separated even over loopback (the
                                world arena is withheld).  Read
                                natively at bootstrap AND by the Python
                                discovery; indexes CURRENT world ranks
                                (an elastic rebuild re-applies it to
                                the dense new ranks; out-of-range
                                ranks are ignored).  Malformed specs
                                abort loudly.  Must agree across ranks.
- ``MPI4JAX_TPU_HIER``        — gate over the hierarchical collective
                                schedules (``hring``/``htree`` and the
                                hierarchical bcast/reduce routing; read
                                natively): ``allow`` (default) lets the
                                decision table / env / API select them
                                on a multi-island comm (bcast/reduce
                                route hierarchically at >= 64 KiB);
                                ``deny`` degrades every hierarchical
                                pick to its flat twin (ring/tree) — a
                                routing kill-switch; ``force`` upgrades
                                every eligible allreduce/allgather to a
                                hierarchical twin and routes
                                bcast/reduce hierarchically at any
                                size.  Must agree across ranks (the
                                schedules exchange different frames).
- ``MPI4JAX_TPU_ICI_LEG``     — gate over the ICI data-plane leg of the
                                hierarchical schedules (``hring``/
                                ``htree``): ``auto`` (default) runs the
                                intra-island phase of an f32 SUM
                                allreduce as a Pallas remote-DMA ring
                                (in-kernel int8 codec under ``+q``)
                                when EVERY multi-member island is an
                                ici-tier TPU slice; ``off`` keeps the
                                native shm/TCP intra paths; ``force``
                                activates the leg regardless of tier
                                (off-TPU it runs the leg's numpy twin /
                                Pallas interpret mode — the dryrun and
                                tier-1 axis).  Must agree across ranks
                                (the leg exchanges different frames
                                than the native intra paths).
- ``MPI4JAX_TPU_PALLAS_COLLECTIVES`` — route eligible mesh-tier collectives
                                (allreduce-SUM, allgather, ring sendrecv)
                                through the Pallas RDMA ring kernels
                                (``ops/pallas_collectives.py``) instead of
                                XLA's builtin collectives.  Reverse-mode AD
                                only: the routed kernels carry a custom_vjp,
                                so ``jvp``/``jacfwd`` through them raises —
                                leave the flag off for forward-mode code.

- ``MPI4JAX_TPU_SERVE_MAX_BATCH`` — serving plane: initial per-iteration
                                decode batch ceiling (positive int,
                                default 8).  The SLO feedback loop may
                                move the live value below/above this
                                within [1, 4x] — the knob sets the
                                starting point, not a hard bound
                                (serving/_scheduler.py).
- ``MPI4JAX_TPU_SERVE_QUEUE_CAP`` — serving plane: bounded admission
                                queue capacity (positive int, default
                                256).  A submit over the cap is SHED
                                with a loud per-request verdict rather
                                than queued (serving/_scheduler.py).
- ``MPI4JAX_TPU_SERVE_SLO_MS``  — serving plane: per-token decode p99
                                SLO target in milliseconds (positive
                                float; default 0 = SLO loop disabled).
                                A rolling window over the per-phase
                                obs percentiles shrinks max-batch when
                                decode p99 overshoots and regrows it
                                when comfortably under
                                (serving/_scheduler.py).
- ``MPI4JAX_TPU_SERVE_ROLES``  — serving plane: prefill/decode role
                                assignment — ``auto`` (default:
                                disaggregate when the topology has >= 2
                                islands and enough ranks, else
                                colocate), ``colocated`` (every rank
                                both prefills and decodes), ``disagg``
                                (force the split; raises on worlds too
                                small to hold both roles).  Strict:
                                anything else aborts loudly — ranks
                                disagreeing on roles would exchange
                                mismatched frames
                                (serving/_roles.py).

- ``MPI4JAX_TPU_LIVE``         — live drift detection + collective
                                re-tuning (``mpi4jax_tpu.live``):
                                ``off`` (default: no controller thread,
                                no collective-boundary hook — pre-live
                                behavior bit-for-bit) or ``auto`` (a
                                controller follows the native obs
                                stream through the non-destructive
                                cursor, flags drift from the cost
                                model's predictions, and swaps the
                                decision table at an epoch rendezvous
                                all ranks reach together).  Strict:
                                ranks disagreeing on the mode would
                                rendezvous on different collective
                                sequences and deadlock.
- ``MPI4JAX_TPU_LIVE_WINDOW``  — rolling event window the controller
                                keeps over the obs stream (positive
                                int, default 256); drift medians and
                                the refit model use only the freshest
                                window (live/_controller.py).
- ``MPI4JAX_TPU_LIVE_DRIFT_PCT`` — percent deviation of an observed
                                per-(op, size band, algorithm) median
                                from the model prediction that counts
                                as drift (positive float, default 30)
                                (live/_drift.py).
- ``MPI4JAX_TPU_LIVE_COOLDOWN_OPS`` — minimum world-collective
                                boundaries between table swaps
                                (positive int, default 64); also paces
                                the epoch-rendezvous probe at
                                cooldown/4 boundaries
                                (live/_swap.py).

There is intentionally no token/notoken routing knob (the reference's
``MPI4JAX_PREFER_NOTOKEN``, utils.py:167-169 there): ordered effects ARE
the core here, and reference-style explicit-token signatures live in
``mpi4jax_tpu.compat.token_api`` as a direct import — an env var that
changes the primary API's return types at a distance would be a footgun.
"""

from __future__ import annotations

import os

#: The complete knob registry: every environment variable the framework
#: (Python *and* native layers, launcher, test harness) reads, with a
#: one-line role.  ``tests/test_config_lint.py`` greps the source tree and
#: fails when a knob is read anywhere without being declared here — the
#: docstring above carries the long-form documentation.
KNOBS = {
    "MPI4JAX_TPU_DEBUG": "per-call debug tracing",
    "MPI4JAX_TPU_NO_WARN_JAX_VERSION": "silence the jax version warning",
    "MPI4JAX_TPU_DISABLE_FFI": "skip the native XLA FFI fast path",
    "MPI4JAX_TPU_DISABLE_SHM": "force TCP collectives on shared hosts",
    "MPI4JAX_TPU_SHM_MB": "shm arena slot size (MB)",
    "MPI4JAX_TPU_SHM_RING_KB": "per-pair shm p2p ring size (KB)",
    "MPI4JAX_TPU_DISABLE_SHM_P2P": "keep p2p on TCP, collectives on shm",
    "MPI4JAX_TPU_STRICT_TOKENS": "chain guard: warn/raise/silent",
    "MPI4JAX_TPU_STAGED_EAGER": "force/forbid staged-eager dispatch",
    "MPI4JAX_TPU_RANK": "world job: this process's rank",
    "MPI4JAX_TPU_SIZE": "world job: world size",
    "MPI4JAX_TPU_COORD": "world job: rendezvous host:base-port",
    "MPI4JAX_TPU_HOSTS": "world job: per-rank host table",
    "MPI4JAX_TPU_HOST": "this rank's reachable address (from_mpi)",
    "MPI4JAX_TPU_SHM_TIMEOUT_S": "shm barrier timeout (seconds)",
    "MPI4JAX_TPU_TIMEOUT_S": "progress-based transport deadline (seconds)",
    "MPI4JAX_TPU_CONNECT_TIMEOUT_S": "bootstrap dial/accept deadline",
    "MPI4JAX_TPU_LAUNCH_GRACE_S": "launcher teardown grace (seconds)",
    "MPI4JAX_TPU_TEST_TIMEOUT_S": "world-test per-test hard deadline",
    "MPI4JAX_TPU_FAULT": "deterministic native fault injection",
    "MPI4JAX_TPU_RETRY": "self-healing link retry budget (0 = disarmed)",
    "MPI4JAX_TPU_RETRY_BACKOFF_MS": "reconnect backoff base (milliseconds)",
    "MPI4JAX_TPU_HEARTBEAT_S": "idle-link heartbeat period (seconds)",
    "MPI4JAX_TPU_WIRE_CRC": "header CRC32C: auto/0/1",
    "MPI4JAX_TPU_RETRY_REPLAY_SLACK": "test-only extra replay frames",
    "MPI4JAX_TPU_JOBID": "unique token for /dev/shm segment names",
    "MPI4JAX_TPU_COLL_ALGO": "force world-tier collective algorithms",
    "MPI4JAX_TPU_COLL_QUANT": "quantized wire formats: allow/deny/force",
    "MPI4JAX_TPU_TUNE_CACHE": "persistent autotune cache path",
    "MPI4JAX_TPU_TUNE_MODEL": "persistent collective cost-model path",
    "MPI4JAX_TPU_TRACE": "record per-op events; dump/merge trace here",
    "MPI4JAX_TPU_TRACE_BUF_KB": "observability event-ring size (KB)",
    "MPI4JAX_TPU_PROGRESS_THREAD": "async progress engine on/off",
    "MPI4JAX_TPU_COALESCE_BYTES": "small-send coalescing threshold",
    "MPI4JAX_TPU_PLAN": "schedule-plan execution (off / plan file / api)",
    "MPI4JAX_TPU_PLAN_BUCKET_KB": "gradient allreduce bucket ceiling (KB)",
    "MPI4JAX_TPU_QUEUE_DEPTH": "progress-engine submission-queue depth",
    "MPI4JAX_TPU_URING": "io_uring submission backend: auto/0/1",
    "MPI4JAX_TPU_PALLAS_COLLECTIVES": "route mesh collectives via Pallas",
    "MPI4JAX_TPU_TOPO": "topology discovery at comm creation: auto/off",
    "MPI4JAX_TPU_FAKE_HOSTS": "virtual host partition for topology tests",
    "MPI4JAX_TPU_HIER": "hierarchical schedules: allow/deny/force",
    "MPI4JAX_TPU_ICI_LEG": "Pallas ICI intra-island leg: auto/off/force",
    "MPI4JAX_TPU_ELASTIC": "elastic worlds: RankFailure + recovery",
    "MPI4JAX_TPU_ELASTIC_DIR": "launcher<->rank generation announcements",
    "MPI4JAX_TPU_ELASTIC_POLICY": "dead-rank policy: shrink / respawn",
    "MPI4JAX_TPU_ELASTIC_GRACE_S": "recover() wait for the next generation",
    "MPI4JAX_TPU_GENERATION": "world generation this process was born into",
    "MPI4JAX_TPU_SLOT": "launcher-slot identity of a respawned rank",
    "MPI4JAX_TPU_CKPT_DIR": "default sharded-checkpoint directory",
    "MPI4JAX_TPU_ANALYZE_TIMEOUT_S": "static verifier wall deadline",
    "MPI4JAX_TPU_ANALYZE_SYMBOLIC": "rank-symbolic analysis: auto/off",
    "MPI4JAX_TPU_NATIVE_LIB": "override path of the native transport .so",
    "MPI4JAX_TPU_SERVE_MAX_BATCH": "serving: initial decode batch ceiling",
    "MPI4JAX_TPU_SERVE_QUEUE_CAP": "serving: bounded admission queue size",
    "MPI4JAX_TPU_SERVE_SLO_MS": "serving: decode p99 SLO target (ms)",
    "MPI4JAX_TPU_SERVE_ROLES": "serving: auto / colocated / disagg",
    "MPI4JAX_TPU_LIVE": "live drift detection + re-tuning: off/auto",
    "MPI4JAX_TPU_LIVE_WINDOW": "live controller rolling window (events)",
    "MPI4JAX_TPU_LIVE_DRIFT_PCT": "drift threshold vs model (percent)",
    "MPI4JAX_TPU_LIVE_COOLDOWN_OPS": "min collective boundaries between swaps",
}

_TRUTHY = frozenset(("1", "true", "on", "yes", "y"))
_FALSY = frozenset(("0", "false", "off", "no", "n", ""))


def parse_bool(value: str, *, name: str = "<flag>") -> bool:
    v = value.strip().lower()
    if v in _TRUTHY:
        return True
    if v in _FALSY:
        return False
    raise ValueError(f"cannot parse boolean env var {name}={value!r}")


def flag(name: str, default: bool = False) -> bool:
    """Read a boolean env var (see module docstring for the known set)."""
    raw = os.environ.get(name)
    if raw is None:
        return default
    return parse_bool(raw, name=name)


def setting(name: str, default: str) -> str:
    return os.environ.get(name, default)


def quant_mode() -> str:
    """``MPI4JAX_TPU_COLL_QUANT`` as "allow" | "deny" | "force" — the
    ONE Python-side reader of the quantized-wire gate, matching the
    native parser byte-for-byte (whitespace-trimmed, loud on anything
    else: a typo'd gate must not silently change numerics — the native
    layer exits on it, so the Python layer must never quietly read the
    same value as "allow")."""
    raw = os.environ.get("MPI4JAX_TPU_COLL_QUANT")
    if raw is None:
        return "allow"
    v = raw.strip()
    if not v:
        return "allow"
    if v in ("allow", "deny", "force"):
        return v
    raise ValueError(
        f"cannot parse MPI4JAX_TPU_COLL_QUANT={raw!r} "
        "(expected allow, deny, or force)")


def topo_mode() -> str:
    """``MPI4JAX_TPU_TOPO`` as "auto" | "off" (strict like quant_mode:
    a typo'd mode must not silently skip — or run — the collective
    discovery handshake on a subset of ranks)."""
    raw = os.environ.get("MPI4JAX_TPU_TOPO")
    if raw is None or not raw.strip():
        return "auto"
    v = raw.strip()
    if v in ("auto", "off"):
        return v
    raise ValueError(
        f"cannot parse MPI4JAX_TPU_TOPO={raw!r} (expected auto or off)")


def hier_mode() -> str:
    """``MPI4JAX_TPU_HIER`` as "allow" | "deny" | "force" — the Python
    mirror of the native gate over the hierarchical schedules, matching
    its parser byte-for-byte (the native layer exits loudly on anything
    else, so this must never quietly read the same value as allow)."""
    raw = os.environ.get("MPI4JAX_TPU_HIER")
    if raw is None:
        return "allow"
    v = raw.strip()
    if not v:
        return "allow"
    if v in ("allow", "deny", "force"):
        return v
    raise ValueError(
        f"cannot parse MPI4JAX_TPU_HIER={raw!r} "
        "(expected allow, deny, or force)")


def ici_leg_mode() -> str:
    """``MPI4JAX_TPU_ICI_LEG`` as "auto" | "off" | "force" — gate over
    the Pallas ICI data-plane leg of the hierarchical schedules (see
    ``topo/_ici_leg.py``).  Strict: a typo aborts loudly rather than
    silently riding the native shm/TCP intra paths."""
    raw = os.environ.get("MPI4JAX_TPU_ICI_LEG")
    if raw is None:
        return "auto"
    v = raw.strip()
    if not v:
        return "auto"
    if v in ("auto", "off", "force"):
        return v
    raise ValueError(
        f"cannot parse MPI4JAX_TPU_ICI_LEG={raw!r} "
        "(expected auto, off, or force)")


def knob_env() -> dict:
    """The RESOLVED tuning-relevant knob environment, for stamping into
    benchmark records and tuner-cache payloads: every committed BENCH
    artifact / derived cache names the gates it was measured under, so
    it is reproducible without reading the shell history.

    Values are the resolved modes (the same resolution the native layer
    applies), not the raw strings — ``{"MPI4JAX_TPU_COLL_QUANT":
    "allow", ...}``.  ``MPI4JAX_TPU_PLAN`` reports ``"0"`` when plan
    execution is off and the spec (a path or ``"1"``) otherwise;
    ``MPI4JAX_TPU_COLL_ALGO`` reports the raw force string or ``""``.
    """
    return {
        "MPI4JAX_TPU_COLL_ALGO":
            os.environ.get("MPI4JAX_TPU_COLL_ALGO", "").strip(),
        "MPI4JAX_TPU_COLL_QUANT": quant_mode(),
        "MPI4JAX_TPU_HIER": hier_mode(),
        "MPI4JAX_TPU_ICI_LEG": ici_leg_mode(),
        "MPI4JAX_TPU_URING": uring_mode(),
        "MPI4JAX_TPU_PLAN": plan_spec() or "0",
    }


def tune_model_path():
    """MPI4JAX_TPU_TUNE_MODEL: an explicit cost-model file path, or
    None (the schedule compiler then never probes the disk for one)."""
    raw = os.environ.get("MPI4JAX_TPU_TUNE_MODEL")
    return raw if raw and raw.strip() else None


def fake_hosts_spec():
    """The raw MPI4JAX_TPU_FAKE_HOSTS spec, or None (parsed by
    ``topo.parse_fake_hosts`` and, independently, natively)."""
    raw = os.environ.get("MPI4JAX_TPU_FAKE_HOSTS")
    return raw if raw and raw.strip() else None


def debug_enabled() -> bool:
    return flag("MPI4JAX_TPU_DEBUG")


def ffi_disabled() -> bool:
    return flag("MPI4JAX_TPU_DISABLE_FFI")


def pallas_collectives_enabled() -> bool:
    return flag("MPI4JAX_TPU_PALLAS_COLLECTIVES")


def _float_knob(name: str, default: float) -> float:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(f"cannot parse {name}={raw!r} as seconds")
    # an explicit non-positive value means OFF, not "use the default" —
    # this mirror must agree with the native parser it reports on
    return v if v > 0 else 0.0


def transport_timeout_s() -> float:
    """Resolved MPI4JAX_TPU_TIMEOUT_S (seconds; 0.0 = no deadline).

    The knob itself is read natively on every wait; this mirror is for
    diagnostics (``runtime.diag``) and documentation tooling.
    """
    return _float_knob("MPI4JAX_TPU_TIMEOUT_S", 0.0)


def connect_timeout_s() -> float:
    """Resolved MPI4JAX_TPU_CONNECT_TIMEOUT_S (seconds; default 30;
    0.0 = explicitly unbounded, matching the native parser)."""
    return _float_knob("MPI4JAX_TPU_CONNECT_TIMEOUT_S", 30.0)


def fault_spec():
    """The raw MPI4JAX_TPU_FAULT spec, or None (parsed/enforced natively)."""
    raw = os.environ.get("MPI4JAX_TPU_FAULT")
    return raw if raw else None


def retry_budget() -> int:
    """Resolved MPI4JAX_TPU_RETRY (reconnect attempts per link failure;
    default 0 = the self-healing layer is disarmed and the wire is
    bit-identical to the historic transport).  The knob itself is read
    natively on every armed path; this mirror serves diag/tooling and
    must agree with the native parser (strict: the native layer exits
    on a malformed value, so this must never quietly read it as 0)."""
    raw = os.environ.get("MPI4JAX_TPU_RETRY")
    if raw is None or not raw.strip():
        return 0
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"cannot parse MPI4JAX_TPU_RETRY={raw!r} as an integer")
    return max(0, v)


def retry_armed() -> bool:
    """True when the self-healing link layer is armed (retry budget > 0)."""
    return retry_budget() > 0


def retry_backoff_ms() -> float:
    """Resolved MPI4JAX_TPU_RETRY_BACKOFF_MS (base reconnect backoff,
    milliseconds; default 100; non-positive restores the default,
    matching the native parser)."""
    raw = os.environ.get("MPI4JAX_TPU_RETRY_BACKOFF_MS")
    if raw is None or not raw.strip():
        return 100.0
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"cannot parse MPI4JAX_TPU_RETRY_BACKOFF_MS={raw!r} as "
            "milliseconds")
    return v if v > 0 else 100.0


def heartbeat_s() -> float:
    """Resolved MPI4JAX_TPU_HEARTBEAT_S (idle-link heartbeat period,
    seconds; default 0.0 = off, matching the native parser)."""
    return _float_knob("MPI4JAX_TPU_HEARTBEAT_S", 0.0)


def wire_crc_mode() -> str:
    """``MPI4JAX_TPU_WIRE_CRC`` as "auto" | "0" | "1" — the Python
    mirror of the native parser, byte-for-byte (whitespace-trimmed,
    loud on anything else).  "auto" resolves to on exactly when
    :func:`retry_armed`; "1" with the retry layer disarmed makes the
    native layer exit loudly (the unarmed wire has no CRC slot)."""
    raw = os.environ.get("MPI4JAX_TPU_WIRE_CRC")
    if raw is None:
        return "auto"
    v = raw.strip()
    if not v:
        return "auto"
    if v in ("auto", "0", "1"):
        return v
    raise ValueError(
        f"cannot parse MPI4JAX_TPU_WIRE_CRC={raw!r} "
        "(expected auto, 0, or 1)")


def retry_replay_slack() -> int:
    """Resolved MPI4JAX_TPU_RETRY_REPLAY_SLACK (test-only: extra
    already-delivered frames replayed per reconnect; default 0)."""
    raw = os.environ.get("MPI4JAX_TPU_RETRY_REPLAY_SLACK")
    if raw is None or not raw.strip():
        return 0
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"cannot parse MPI4JAX_TPU_RETRY_REPLAY_SLACK={raw!r} as "
            "an integer")
    return max(0, v)


def analyze_symbolic_mode() -> str:
    """``MPI4JAX_TPU_ANALYZE_SYMBOLIC`` as "auto" | "off" (strict like
    topo_mode: a typo'd mode aborts loudly rather than silently
    changing which verification path produced a verdict).  Mirrors
    ``analysis._symbolic.symbolic_mode`` byte-for-byte — the analysis
    package reads the environment directly to stay standalone-loadable,
    and the two parsers must never drift apart."""
    raw = os.environ.get("MPI4JAX_TPU_ANALYZE_SYMBOLIC")
    if raw is None or not raw.strip():
        return "auto"
    v = raw.strip()
    if v in ("auto", "off"):
        return v
    raise ValueError(
        f"cannot parse MPI4JAX_TPU_ANALYZE_SYMBOLIC={raw!r} "
        "(expected auto or off)")


def analyze_timeout_s() -> float:
    """Resolved MPI4JAX_TPU_ANALYZE_TIMEOUT_S (seconds; default 120;
    0 = no deadline, matching MPI4JAX_TPU_TIMEOUT_S's convention)."""
    return _float_knob("MPI4JAX_TPU_ANALYZE_TIMEOUT_S", 120.0)


def native_lib_override():
    """MPI4JAX_TPU_NATIVE_LIB: an explicit transport .so path, or None."""
    raw = os.environ.get("MPI4JAX_TPU_NATIVE_LIB")
    return raw if raw else None


def progress_thread_enabled() -> bool:
    """Resolved MPI4JAX_TPU_PROGRESS_THREAD (default True).

    The knob itself is read natively on every op; this mirror is for
    diagnostics (``runtime.diag``) and documentation tooling."""
    raw = os.environ.get("MPI4JAX_TPU_PROGRESS_THREAD")
    if raw is None or not raw.strip():
        return True
    return parse_bool(raw, name="MPI4JAX_TPU_PROGRESS_THREAD")


def coalesce_bytes() -> int:
    """Resolved MPI4JAX_TPU_COALESCE_BYTES (default 4096; 0 = off),
    mirroring the native parser's clamps for diagnostics."""
    raw = os.environ.get("MPI4JAX_TPU_COALESCE_BYTES")
    if raw is None or not raw.strip():
        return 4096
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"cannot parse MPI4JAX_TPU_COALESCE_BYTES={raw!r} as bytes")
    return max(0, min(v, 64 * 1024))


def uring_mode() -> str:
    """``MPI4JAX_TPU_URING`` as "auto" | "0" | "1" — the Python mirror
    of the native parser, byte-for-byte (whitespace-trimmed, loud on
    anything else: the native layer exits on a typo'd knob, so this
    must never quietly read the same value as "auto").  Whether the
    backend is ACTUALLY active is resolved natively by the kernel
    probe — ``runtime.bridge.uring_status()`` reports on/off/
    unavailable(<reason>)."""
    raw = os.environ.get("MPI4JAX_TPU_URING")
    if raw is None:
        return "auto"
    v = raw.strip()
    if not v:
        return "auto"
    if v in ("auto", "0", "1"):
        return v
    raise ValueError(
        f"cannot parse MPI4JAX_TPU_URING={raw!r} (expected auto, 0, or 1)")


def uring_active() -> bool:
    """True when the loaded native transport resolved the io_uring
    backend ON (knob allows it AND the kernel probe succeeded).  False
    on ``MPI4JAX_TPU_URING=0``, an incapable kernel, or a pre-uring
    native library.  Mirror for diagnostics/tooling — the native layer
    is the single authority."""
    if uring_mode() == "0":
        return False
    from ..runtime import bridge

    status = bridge.uring_status()
    return status is not None and status.startswith("on")


def trace_path():
    """MPI4JAX_TPU_TRACE: the recording dump/merge base path, or None
    (observability recorder off)."""
    raw = os.environ.get("MPI4JAX_TPU_TRACE")
    return raw if raw else None


def plan_spec():
    """MPI4JAX_TPU_PLAN: a plan-file path or enable flag, or None when
    plan execution is off (the resolution itself lives in
    runtime/planrt.py; this mirror serves diag and the FFI gate)."""
    raw = os.environ.get("MPI4JAX_TPU_PLAN", "").strip()
    if not raw or raw.lower() in ("0", "false", "off", "no"):
        return None
    return raw


def elastic_enabled() -> bool:
    """Resolved MPI4JAX_TPU_ELASTIC (default False): transport failures
    raise :class:`mpi4jax_tpu.elastic.RankFailure` instead of
    hard-exiting the process (``runtime/bridge.py`` reads this on its
    abort path; ``launch --elastic`` sets it)."""
    return flag("MPI4JAX_TPU_ELASTIC")


def elastic_dir():
    """MPI4JAX_TPU_ELASTIC_DIR: the launcher<->rank coordination
    directory for generation announcements, or None."""
    raw = os.environ.get("MPI4JAX_TPU_ELASTIC_DIR")
    return raw if raw else None


def elastic_policy() -> str:
    """MPI4JAX_TPU_ELASTIC_POLICY as "shrink" | "respawn" (strict like
    quant_mode: a typo'd policy must not silently shrink a job whose
    operator asked for respawn)."""
    raw = os.environ.get("MPI4JAX_TPU_ELASTIC_POLICY")
    if raw is None or not raw.strip():
        return "shrink"
    v = raw.strip()
    if v in ("shrink", "respawn"):
        return v
    raise ValueError(
        f"cannot parse MPI4JAX_TPU_ELASTIC_POLICY={raw!r} "
        "(expected shrink or respawn)")


def elastic_grace_s() -> float:
    """Resolved MPI4JAX_TPU_ELASTIC_GRACE_S (seconds, default 60):
    how long ``elastic.recover()`` waits for the launcher's next
    generation announcement."""
    v = _float_knob("MPI4JAX_TPU_ELASTIC_GRACE_S", 60.0)
    return v if v > 0 else 60.0


def generation() -> int:
    """The world generation this process was BORN into (default 0; the
    elastic launcher exports it to respawned children).  The live
    generation after in-process recoveries is tracked by
    ``mpi4jax_tpu.elastic`` on top of this."""
    raw = os.environ.get("MPI4JAX_TPU_GENERATION")
    if raw is None or not raw.strip():
        return 0
    try:
        return int(raw)
    except ValueError:
        raise ValueError(
            f"cannot parse MPI4JAX_TPU_GENERATION={raw!r} as an integer")


def ckpt_dir():
    """MPI4JAX_TPU_CKPT_DIR: the default sharded-checkpoint directory,
    or None (callers must pass one explicitly)."""
    raw = os.environ.get("MPI4JAX_TPU_CKPT_DIR")
    return raw if raw else None


def plan_bucket_bytes() -> int:
    """Resolved MPI4JAX_TPU_PLAN_BUCKET_KB in bytes (default 1 MiB;
    0 disables gradient bucketing)."""
    raw = os.environ.get("MPI4JAX_TPU_PLAN_BUCKET_KB")
    if raw is None or not raw.strip():
        return 1 << 20
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(
            f"cannot parse MPI4JAX_TPU_PLAN_BUCKET_KB={raw!r} as KB")
    return max(0, v) * 1024


def _positive_int_knob(name: str, default: int) -> int:
    raw = os.environ.get(name)
    if raw is None or not raw.strip():
        return default
    try:
        v = int(raw)
    except ValueError:
        raise ValueError(f"cannot parse {name}={raw!r} as an integer")
    if v <= 0:
        raise ValueError(f"{name}={raw!r} must be a positive integer")
    return v


def serve_max_batch() -> int:
    """``MPI4JAX_TPU_SERVE_MAX_BATCH``: the serving scheduler's initial
    per-iteration decode batch ceiling (strict positive int, default 8).
    The SLO loop adjusts the live value from this starting point."""
    return _positive_int_knob("MPI4JAX_TPU_SERVE_MAX_BATCH", 8)


def serve_queue_cap() -> int:
    """``MPI4JAX_TPU_SERVE_QUEUE_CAP``: bounded admission-queue capacity
    (strict positive int, default 256).  Submits over the cap are shed
    with a loud verdict, never silently queued."""
    return _positive_int_knob("MPI4JAX_TPU_SERVE_QUEUE_CAP", 256)


def serve_slo_ms() -> float:
    """``MPI4JAX_TPU_SERVE_SLO_MS``: per-token decode p99 target in
    milliseconds for the serving SLO feedback loop.  Strict: a
    non-numeric or negative value aborts loudly (a typo'd SLO silently
    disabling adaptation would defeat the loop); 0 / unset = loop
    disabled."""
    raw = os.environ.get("MPI4JAX_TPU_SERVE_SLO_MS")
    if raw is None or not raw.strip():
        return 0.0
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"cannot parse MPI4JAX_TPU_SERVE_SLO_MS={raw!r} as ms")
    if v < 0:
        raise ValueError(
            f"MPI4JAX_TPU_SERVE_SLO_MS={raw!r} must be >= 0")
    return v


def serve_roles() -> str:
    """``MPI4JAX_TPU_SERVE_ROLES`` as "auto" | "colocated" | "disagg" —
    the serving plane's prefill/decode role-assignment mode.  Strict
    like the other cross-rank gates: ranks disagreeing on roles would
    exchange mismatched frames, so a typo aborts loudly instead of
    silently colocating."""
    raw = os.environ.get("MPI4JAX_TPU_SERVE_ROLES")
    if raw is None or not raw.strip():
        return "auto"
    v = raw.strip()
    if v in ("auto", "colocated", "disagg"):
        return v
    raise ValueError(
        f"cannot parse MPI4JAX_TPU_SERVE_ROLES={raw!r} "
        "(expected auto, colocated, or disagg)")


def live_mode() -> str:
    """``MPI4JAX_TPU_LIVE`` as "off" | "auto" — the live re-tuning
    subsystem (``mpi4jax_tpu.live``): a controller thread that watches
    the native obs stream for drift from the cost model's predictions
    and swaps the collective decision table at an agreed boundary.
    Strict like the other cross-rank gates: ranks disagreeing on the
    mode would rendezvous on different collective sequences and
    deadlock, so a typo aborts loudly.  The "off" default arms nothing
    — no thread, no boundary hook, no obs-ring enable — pinning
    pre-live behavior bit-for-bit."""
    raw = os.environ.get("MPI4JAX_TPU_LIVE")
    if raw is None or not raw.strip():
        return "off"
    v = raw.strip()
    if v in ("off", "auto"):
        return v
    raise ValueError(
        f"cannot parse MPI4JAX_TPU_LIVE={raw!r} (expected off or auto)")


def live_window() -> int:
    """``MPI4JAX_TPU_LIVE_WINDOW``: the live controller's rolling
    window over the native obs stream, in events (strict positive int,
    default 256).  Drift medians and the refit model both come from
    the freshest ``window`` events only — stale timings never pool
    with the current contention regime's."""
    return _positive_int_knob("MPI4JAX_TPU_LIVE_WINDOW", 256)


def live_drift_pct() -> float:
    """``MPI4JAX_TPU_LIVE_DRIFT_PCT``: how far (percent) an observed
    per-(op, size band, algorithm) median may deviate from the cost
    model's prediction before the controller declares drift and
    prepares a candidate table (strict positive float, default 30).
    Strict: a typo'd threshold silently never (or always) firing would
    defeat the loop."""
    raw = os.environ.get("MPI4JAX_TPU_LIVE_DRIFT_PCT")
    if raw is None or not raw.strip():
        return 30.0
    try:
        v = float(raw)
    except ValueError:
        raise ValueError(
            f"cannot parse MPI4JAX_TPU_LIVE_DRIFT_PCT={raw!r} as percent")
    if v <= 0:
        raise ValueError(
            f"MPI4JAX_TPU_LIVE_DRIFT_PCT={raw!r} must be > 0")
    return v


def live_cooldown_ops() -> int:
    """``MPI4JAX_TPU_LIVE_COOLDOWN_OPS``: minimum world-collective
    boundaries between table swaps (strict positive int, default 64).
    Also paces the epoch rendezvous itself — ranks compare epochs every
    ``cooldown / 4`` boundaries (at least every boundary), so a
    proposed table is installed well within one cooldown of drift
    detection while a quiescent run pays a 16-byte bcast at most every
    few boundaries."""
    return _positive_int_knob("MPI4JAX_TPU_LIVE_COOLDOWN_OPS", 64)
