"""Supported dtypes and their wire codes.

Parity with the reference's dtype→MPI-datatype table
(/root/reference/mpi4jax/_src/utils.py:100-115, 14 dtypes) plus bfloat16,
which is the native TPU matmul dtype and therefore first-class here.

The integer codes are the wire protocol between Python and the native C++
transport (native/tpucomm.cc) — they must stay in sync with ``tpucomm.h``.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# name -> (dtype, wire code, element size in bytes)
_TABLE = {
    "bool": (jnp.bool_, 0, 1),
    "int8": (jnp.int8, 1, 1),
    "int16": (jnp.int16, 2, 2),
    "int32": (jnp.int32, 3, 4),
    "int64": (jnp.int64, 4, 8),
    "uint8": (jnp.uint8, 5, 1),
    "uint16": (jnp.uint16, 6, 2),
    "uint32": (jnp.uint32, 7, 4),
    "uint64": (jnp.uint64, 8, 8),
    "float16": (jnp.float16, 9, 2),
    "bfloat16": (jnp.bfloat16, 10, 2),
    "float32": (jnp.float32, 11, 4),
    "float64": (jnp.float64, 12, 8),
    "complex64": (jnp.complex64, 13, 8),
    "complex128": (jnp.complex128, 14, 16),
}

SUPPORTED_DTYPES = tuple(np.dtype(v[0]) for v in _TABLE.values())


def wire_code(dtype) -> int:
    """Wire code for ``dtype``; raises TypeError for unsupported dtypes."""
    name = np.dtype(dtype).name
    try:
        return _TABLE[name][1]
    except KeyError:
        raise TypeError(
            f"mpi4jax_tpu does not support dtype {name}; supported: "
            f"{sorted(_TABLE)}"
        ) from None


def check_supported(dtype) -> None:
    wire_code(dtype)


def is_boolean(dtype) -> bool:
    return np.dtype(dtype) == np.dtype(np.bool_)


def is_inexact(dtype) -> bool:
    return np.issubdtype(np.dtype(dtype), np.inexact)
