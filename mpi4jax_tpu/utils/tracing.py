"""Debug tracing: per-call entry/exit log lines with rank, call id, timing.

Parity with the reference's single observability mechanism
(/root/reference/mpi4jax/_src/xla_bridge/mpi_xla_bridge.pyx:38-60,100-112 and
SURVEY.md §5.1): when enabled (``MPI4JAX_TPU_DEBUG=1`` or ``set_logging``),
every communicating call emits

    r<rank> | <id8> | <Op> <details>
    r<rank> | <id8> | <Op> done with code 0 (<dt> s)

The world tier logs at execution time from the host side (the C++ transport
has its own mirror of this, native/tpucomm.cc).  The mesh tier executes on
device inside a compiled program, so per-execution host logging is done via
``jax.debug.callback`` when tracing is enabled at trace time.
"""

from __future__ import annotations

import secrets
import time

from . import config

_PRINT_DEBUG: bool | None = None


def set_logging(enabled: bool) -> None:
    global _PRINT_DEBUG
    _PRINT_DEBUG = bool(enabled)


def logging_enabled() -> bool:
    if _PRINT_DEBUG is not None:
        return _PRINT_DEBUG
    return config.debug_enabled()


def new_call_id() -> str:
    return secrets.token_hex(4)


def log_line(rank, call_id: str, message: str) -> None:
    print(f"r{rank} | {call_id} | {message}", flush=True)


class CallTrace:
    """Context manager for host-side op tracing (world tier).

    ``details`` may be a zero-arg callable, evaluated only when logging
    is enabled — hot-path callers (e.g. the collective-algorithm name
    lookup, a native call per op) pay nothing when tracing is off.
    """

    def __init__(self, rank: int, opname: str, details=""):
        self.rank = rank
        self.opname = opname
        self.details = details
        self.call_id = new_call_id()
        self._t0 = 0.0

    def __enter__(self):
        if logging_enabled():
            details = self.details() if callable(self.details) else self.details
            log_line(
                self.rank, self.call_id, f"{self.opname} {details}".rstrip()
            )
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if logging_enabled() and exc_type is None:
            dt = time.perf_counter() - self._t0
            log_line(
                self.rank,
                self.call_id,
                f"{self.opname} done with code 0 ({dt:.6f} s)",
            )
        return False


def trace_mesh_op(axis_rank, opname: str, details: str = "") -> None:
    """Emit a device-side debug line for a mesh-tier op (if enabled).

    Uses ``jax.debug.callback`` so the line is printed at *execution* time
    with the concrete rank, matching the world-tier format.
    """
    if not logging_enabled():
        return
    import jax

    call_id = new_call_id()

    def _emit(r):
        log_line(int(r), call_id, f"{opname} {details}".rstrip())

    jax.debug.callback(_emit, axis_rank)
