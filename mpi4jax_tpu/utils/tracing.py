"""Debug tracing: per-call entry/exit log lines with rank, call id, timing.

Parity with the reference's single observability mechanism
(/root/reference/mpi4jax/_src/xla_bridge/mpi_xla_bridge.pyx:38-60,100-112 and
SURVEY.md §5.1): when enabled (``MPI4JAX_TPU_DEBUG=1`` or ``set_logging``),
every communicating call emits

    r<rank> | <id8> | <Op> <details>
    r<rank> | <id8> | <Op> done with code 0 (<dt> s)

Lines go to **stderr** in one atomic ``write`` each: stdout belongs to
programs' own output (benchmarks emit JSON results there — a debug line
spliced into a JSON record corrupts it), and per-line atomicity keeps
multi-rank output from interleaving mid-line (the native transport's
mirror of this format, ``native/tpucomm.cc``, already behaves this way).

The world tier logs at execution time from the host side.  The mesh tier
executes on device inside a compiled program, so per-execution host
logging is done via ``jax.debug.callback`` when tracing is enabled at
trace time.

``CallTrace`` additionally feeds the structured observability recorder
(``mpi4jax_tpu.obs``) when it is armed: every traced op becomes a span
with peer/bytes/tag fields in the recording, independent of whether the
debug *lines* are enabled.
"""

from __future__ import annotations

import itertools
import sys
import time

from . import config

_PRINT_DEBUG: bool | None = None


def set_logging(enabled: bool) -> None:
    global _PRINT_DEBUG
    _PRINT_DEBUG = bool(enabled)


def logging_enabled() -> bool:
    if _PRINT_DEBUG is not None:
        return _PRINT_DEBUG
    return config.debug_enabled()


# Monotonic per-rank call counter: the previous implementation drew
# secrets.token_hex(4) — an os.urandom syscall — on EVERY traced call,
# measurable on microsecond-scale ops.  The 8-hex-digit line format is
# unchanged; ids now count up (and are trivially sortable in logs).
_CALL_COUNTER = itertools.count()


def new_call_id() -> str:
    return f"{next(_CALL_COUNTER) & 0xFFFFFFFF:08x}"


def log_line(rank, call_id: str, message: str) -> None:
    # one write() per line: atomic up to PIPE_BUF, so concurrent ranks
    # sharing the launcher's stderr cannot interleave mid-line
    sys.stderr.write(f"r{rank} | {call_id} | {message}\n")
    sys.stderr.flush()


_obs_state = None  # lazily-bound obs._recorder module (import once)


def _obs_enabled() -> bool:
    # disabled-path cost: one global check + one module-attribute read
    # (the import runs once, on the first traced call ever)
    global _obs_state
    if _obs_state is None:
        from ..obs import _recorder

        _obs_state = _recorder
    return _obs_state._ENABLED


class CallTrace:
    """Context manager for host-side op tracing (world tier).

    ``details`` may be a zero-arg callable, evaluated only when logging
    is enabled — hot-path callers (e.g. the collective-algorithm name
    lookup, a native call per op) pay nothing when tracing is off.

    ``peer``/``nbytes``/``tag``/``algo`` label the recorded span when
    the observability recorder (``mpi4jax_tpu.obs``) is armed; they are
    never formatted into the debug lines.

    The disabled path is deliberately thin (slots, no call-id draw, no
    clock reads): this wrapper sits on every world-tier op, where the
    whole dispatch budget is a few microseconds (the async-progress-
    engine PR measured the old ~3 us disabled cost as a visible share
    of the 1 KB in-jit latency).
    """

    __slots__ = ("rank", "opname", "details", "call_id", "peer", "nbytes",
                 "tag", "algo", "_t0", "_t0_unix", "_log", "_obs")

    def __init__(self, rank: int, opname: str, details="", *, peer=-1,
                 nbytes=0, tag=0, algo=None):
        self.rank = rank
        self.opname = opname
        self.details = details
        self.peer = peer
        self.nbytes = nbytes
        self.tag = tag
        self.algo = algo

    def __enter__(self):
        self._log = logging_enabled()
        self._obs = _obs_enabled()
        if self._log:
            self.call_id = new_call_id()
            details = self.details() if callable(self.details) else self.details
            log_line(
                self.rank, self.call_id, f"{self.opname} {details}".rstrip()
            )
        if self._log or self._obs:
            if self._obs:
                self._t0_unix = time.time()
            self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is None and (self._log or self._obs):
            dt = time.perf_counter() - self._t0
            if self._log:
                log_line(
                    self.rank,
                    self.call_id,
                    f"{self.opname} done with code 0 ({dt:.6f} s)",
                )
            if self._obs:
                _obs_state.record_span(
                    self.opname, self._t0_unix, dt, peer=self.peer,
                    nbytes=self.nbytes, tag=self.tag, algo=self.algo,
                )
        return False


def trace_mesh_op(axis_rank, opname: str, details: str = "") -> None:
    """Emit a device-side debug line for a mesh-tier op (if enabled).

    Uses ``jax.debug.callback`` so the line is printed at *execution* time
    with the concrete rank, matching the world-tier format.
    """
    if not logging_enabled():
        return
    import jax

    call_id = new_call_id()

    def _emit(r):
        log_line(int(r), call_id, f"{opname} {details}".rstrip())

    jax.debug.callback(_emit, axis_rank)
