"""Runtime argument validation for the public op functions.

Functional parity with the reference's ``@enforce_types`` decorator
(/root/reference/mpi4jax/_src/validation.py:8-94): every public op checks its
static arguments eagerly so users get a readable error at call time instead of
a trace-time stack, with a dedicated message when a traced value leaks into a
static-only parameter (the reference's "abstract tracer" sharp bit).

Implementation is intentionally different: a small spec-dict checker rather
than an annotation-driven reflection layer — there are only a handful of
static parameter kinds in this API (ints, ReduceOps, comms, perms).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


class ValidationError(TypeError):
    pass


def _is_tracer(value: Any) -> bool:
    return isinstance(value, jax.core.Tracer) and not isinstance(
        value, jax.numpy.ndarray
    )


def _describe(value: Any) -> str:
    return f"{type(value).__module__}.{type(value).__qualname__}"


def check_static_int(name: str, value: Any, *, allow_none: bool = False):
    """Check that ``value`` is a concrete Python/NumPy integer.

    Traced values get a dedicated error: static parameters become part of the
    compiled program (e.g. a ppermute permutation or a primitive param) and
    cannot be data-dependent.
    """
    if value is None and allow_none:
        return None
    if isinstance(value, jax.core.Tracer):
        raise ValidationError(
            f"{name} must be a static (concrete) integer, but got a traced "
            f"value. Values that select ranks/roots/tags are compiled into "
            f"the program and cannot depend on runtime data. If you are "
            f"inside jit/shard_map, pass a Python int (closure/static arg)."
        )
    if isinstance(value, (bool, np.bool_)):
        raise ValidationError(f"{name} must be an integer, got bool")
    if not isinstance(value, (int, np.integer)):
        raise ValidationError(
            f"{name} must be an integer, got {_describe(value)}"
        )
    return int(value)


def check_array(name: str, value: Any):
    """Check that ``value`` is array-like (jax array, tracer, numpy, scalar)."""
    if isinstance(value, (jax.Array, jax.core.Tracer)):
        return value
    if isinstance(value, (np.ndarray, np.generic, int, float, complex, bool)):
        return value
    raise ValidationError(
        f"{name} must be an array or scalar, got {_describe(value)}"
    )


def op_context(op_name: str, comm=None, x=None) -> str:
    """Uniform context suffix for ops-layer errors.

    Every validation failure names the op, the rank (or mesh axes — a
    MeshComm's rank is a traced value, so the axes stand in for it), and
    the offending array's dtype/shape: ``[allreduce, rank 2/4, dtype
    float32, shape (4,)]``.  A multi-process job surfaces one rank's
    traceback; this suffix is what lets the reader place it without
    re-running under a debugger.
    """
    bits = [op_name]
    if comm is not None:
        rank = getattr(comm, "_rank", None)
        if isinstance(rank, (int, np.integer)):
            bits.append(f"rank {int(rank)}/{comm.size()}")
        else:
            axes = getattr(comm, "axes", None)
            bits.append(f"mesh axes {axes!r}" if axes else "mesh tier")
    if x is not None:
        try:
            aval = _get_aval(x)
            bits.append(f"dtype {np.dtype(aval.dtype).name}")
            bits.append(f"shape {tuple(aval.shape)}")
        except Exception:
            pass
    return " [" + ", ".join(bits) + "]"


def _get_aval(x):
    from jax._src import core as _jcore  # stable across jax 0.4-0.9

    return _jcore.get_aval(x)


def fail(msg: str, *, op: str, comm=None, x=None, exc=None):
    """Raise a :class:`ValidationError` (or ``exc``) with op context."""
    exc = exc or ValidationError
    raise exc(msg + op_context(op, comm, x))


def check_reduce_dtype(op_name: str, reduce_op, x, comm):
    """Run ``reduce_op.check_dtype`` and re-raise with full op context."""
    try:
        reduce_op.check_dtype(_result_dtype(x))
    except TypeError as err:
        raise ValidationError(
            f"{err}{op_context(op_name, comm, x)}"
        ) from None


def check_wire_dtype(op_name: str, x, comm):
    """Fail fast — with op/rank/dtype/shape context — on dtypes the native
    wire protocol cannot carry, instead of a bare bridge-layer TypeError
    deep inside a compiled callback."""
    from . import dtypes as _dtypes

    try:
        _dtypes.wire_code(_result_dtype(x))
    except TypeError as err:
        raise ValidationError(
            f"{err}{op_context(op_name, comm, x)}"
        ) from None


def _result_dtype(x):
    try:
        return _get_aval(x).dtype  # tracers, jax/np arrays
    except Exception:
        return np.result_type(x)   # python scalars


def check_in_range(name: str, value: int, size: int, *, op=None, comm=None):
    if not 0 <= value < size:
        context = op_context(op, comm) if op else ""
        raise ValidationError(
            f"{name}={value} out of range for communicator of size "
            f"{size}{context}"
        )
    return value
