"""Runtime argument validation for the public op functions.

Functional parity with the reference's ``@enforce_types`` decorator
(/root/reference/mpi4jax/_src/validation.py:8-94): every public op checks its
static arguments eagerly so users get a readable error at call time instead of
a trace-time stack, with a dedicated message when a traced value leaks into a
static-only parameter (the reference's "abstract tracer" sharp bit).

Implementation is intentionally different: a small spec-dict checker rather
than an annotation-driven reflection layer — there are only a handful of
static parameter kinds in this API (ints, ReduceOps, comms, perms).
"""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


class ValidationError(TypeError):
    pass


def _is_tracer(value: Any) -> bool:
    return isinstance(value, jax.core.Tracer) and not isinstance(
        value, jax.numpy.ndarray
    )


def _describe(value: Any) -> str:
    return f"{type(value).__module__}.{type(value).__qualname__}"


def check_static_int(name: str, value: Any, *, allow_none: bool = False):
    """Check that ``value`` is a concrete Python/NumPy integer.

    Traced values get a dedicated error: static parameters become part of the
    compiled program (e.g. a ppermute permutation or a primitive param) and
    cannot be data-dependent.
    """
    if value is None and allow_none:
        return None
    if isinstance(value, jax.core.Tracer):
        raise ValidationError(
            f"{name} must be a static (concrete) integer, but got a traced "
            f"value. Values that select ranks/roots/tags are compiled into "
            f"the program and cannot depend on runtime data. If you are "
            f"inside jit/shard_map, pass a Python int (closure/static arg)."
        )
    if isinstance(value, (bool, np.bool_)):
        raise ValidationError(f"{name} must be an integer, got bool")
    if not isinstance(value, (int, np.integer)):
        raise ValidationError(
            f"{name} must be an integer, got {_describe(value)}"
        )
    return int(value)


def check_array(name: str, value: Any):
    """Check that ``value`` is array-like (jax array, tracer, numpy, scalar)."""
    if isinstance(value, (jax.Array, jax.core.Tracer)):
        return value
    if isinstance(value, (np.ndarray, np.generic, int, float, complex, bool)):
        return value
    raise ValidationError(
        f"{name} must be an array or scalar, got {_describe(value)}"
    )


def check_in_range(name: str, value: int, size: int):
    if not 0 <= value < size:
        raise ValidationError(
            f"{name}={value} out of range for communicator of size {size}"
        )
    return value
