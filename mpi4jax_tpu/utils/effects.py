"""JAX effect types for the world-tier (multi-process) primitives.

The reference defines two effects with *stable hashes* so that jaxprs cached
on different processes agree (/root/reference/mpi4jax/_src/utils.py:16-31),
registering the notoken one as ordered (jax_compat.py:82-100 there).  Same
contract here: ``CommEffect`` is ordered (serializes every world-tier call —
the framework's correctness backbone), ``UnorderedCommEffect`` marks calls
that are safe to reorder (e.g. the transposed allreduce pass, which lowers to
identity).
"""

from __future__ import annotations

from jax._src import effects as _effects


class _StableHashEffect(_effects.Effect):
    """Effect whose hash depends only on the class name.

    Python object hashes differ across processes; jaxpr caches keyed on
    effects must agree across all ranks of a world communicator.
    """

    def __hash__(self):
        return hash(type(self).__module__ + type(self).__qualname__)

    def __eq__(self, other):
        return type(self) is type(other)

    def __repr__(self):
        return type(self).__qualname__


class CommEffect(_StableHashEffect):
    pass


class UnorderedCommEffect(_StableHashEffect):
    pass


comm_effect = CommEffect()
unordered_comm_effect = UnorderedCommEffect()

# Ordered: the compiler threads a runtime token through every op carrying
# this effect, in program order — the notoken design the reference's
# experimental layer pioneered (SURVEY.md §2.2), promoted to the core here.
_effects.ordered_effects.add_type(CommEffect)
_effects.lowerable_effects.add_type(CommEffect)
_effects.lowerable_effects.add_type(UnorderedCommEffect)
_effects.control_flow_allowed_effects.add_type(CommEffect)
_effects.control_flow_allowed_effects.add_type(UnorderedCommEffect)
_effects.custom_derivatives_allowed_effects.add_type(CommEffect)
_effects.custom_derivatives_allowed_effects.add_type(UnorderedCommEffect)
