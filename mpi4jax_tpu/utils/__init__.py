from . import config, dtypes, jax_compat, tracing, validation  # noqa: F401
