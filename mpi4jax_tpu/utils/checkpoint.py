"""Checkpoint / resume for model state pytrees.

The reference has no checkpointing at all (SURVEY.md §5.4); this
framework ships a dependency-light implementation that the elastic
recovery subsystem (``mpi4jax_tpu.elastic``, docs/elasticity.md) builds
on:

- :func:`save` / :func:`restore` — one pytree, one ``.npz`` file
  (orbax when installed and the path is not ``.npz``-shaped).  Writes
  are ATOMIC: the payload lands in ``<path>.tmp.<pid>`` and is
  ``os.replace``d into place, so a crash mid-save can never corrupt the
  previous checkpoint.
- :func:`save_sharded` / :func:`restore_sharded` — one directory per
  step holding one shard file per rank plus a ``manifest.json`` that is
  written LAST, after a cross-rank barrier confirmed every shard is
  durable.  A checkpoint *exists* iff its manifest does; a kill at ANY
  point of the save leaves either the previous committed step intact or
  a manifest-less directory that :func:`latest_step` ignores — never a
  torn checkpoint.  Manifests are generation-stamped (elastic worlds).

Leaves are serialized as raw bytes with the dtype NAME recorded in a
JSON descriptor inside the archive — numpy's ``.npz`` round-trips
builtin dtypes only (an ``ml_dtypes.bfloat16`` array comes back as
opaque ``V2`` records), and training state is full of bf16.

jax is optional to this MODULE: tree flattening uses ``jax.tree`` when
importable and falls back to a pure-Python walk over dict/list/tuple
(sorted dict keys and None-as-empty-subtree, matching jax's semantics),
so any jax version works — there is no >= 0.6 gate here — and the
module even loads standalone where jax cannot import (the packaged
``mpi4jax_tpu.utils`` import path does pull in jax via its
``__init__``; load ``checkpoint.py`` with a synthetic parent package to
avoid that, as ``tests/test_checkpoint_commit.py`` demonstrates).

Single-controller semantics: arrays are fetched to host and restored
with whatever sharding the consumer applies.  For world-tier jobs use
the sharded API; a DP-replicated tree (every rank holds the same
params — the ``parallel.dp`` pattern) restores onto ANY world size,
which is what lets a job resume after the world shrank.
"""

from __future__ import annotations

import json
import os
from typing import Any, Optional

import numpy as np

from . import config

MANIFEST = "manifest.json"
_META_KEY = "__m4j_meta__"
_FORMAT = 2


def _try_orbax():
    try:
        import orbax.checkpoint as ocp  # type: ignore

        return ocp
    except Exception:
        return None


# ---------------- pytree handling (jax optional) ----------------


def _flatten(tree: Any):
    """(leaves, rebuild) — ``jax.tree`` when available, else a pure-
    Python walk over dict/list/tuple (dict keys sorted, jax's order)."""
    try:
        import jax

        leaves, treedef = jax.tree.flatten(tree)
        return list(leaves), ("jax", treedef)
    except ImportError:
        leaves = []

        def walk(t):
            if t is None:
                return  # jax semantics: None is an empty subtree
            if isinstance(t, dict):
                for k in sorted(t):
                    walk(t[k])
            elif isinstance(t, (list, tuple)):
                for x in t:
                    walk(x)
            else:
                leaves.append(t)

        walk(tree)
        return leaves, ("py", tree)


def _unflatten(treedef, leaves):
    kind, td = treedef
    if kind == "jax":
        import jax

        return jax.tree.unflatten(td, list(leaves))
    it = iter(leaves)

    def build(t):
        if t is None:
            return None  # empty subtree, consumes no leaf (jax semantics)
        if isinstance(t, dict):
            return {k: build(t[k]) for k in sorted(t)}
        if isinstance(t, tuple):
            vals = [build(x) for x in t]
            return type(t)(*vals) if hasattr(t, "_fields") else tuple(vals)
        if isinstance(t, list):
            return [build(x) for x in t]
        return next(it)

    return build(td)


# ---------------- leaf codec + atomic npz ----------------


def _write_npz(path: str, tree: Any, extra_meta: Optional[dict] = None
               ) -> None:
    """Atomically write one pytree as an npz archive: every leaf as raw
    bytes (``leaf_<i>`` uint8) plus a JSON descriptor naming dtype and
    shape — the only encoding that round-trips bf16 and friends."""
    leaves, _ = _flatten(tree)
    arrays = {}
    metas = []
    for i, leaf in enumerate(leaves):
        arr = np.asarray(leaf)
        arr = arr if arr.flags.c_contiguous else np.ascontiguousarray(arr)
        arrays[f"leaf_{i}"] = arr.reshape(-1).view(np.uint8)
        metas.append({"dtype": arr.dtype.name, "shape": list(arr.shape)})
    meta = {"format": _FORMAT, "nleaves": len(leaves), "leaves": metas}
    meta.update(extra_meta or {})
    arrays[_META_KEY] = np.frombuffer(
        json.dumps(meta).encode(), np.uint8).copy()
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(f, **arrays)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _read_npz(path: str):
    """(leaves, meta) back from :func:`_write_npz`; also reads the
    legacy format-1 files (plain ``leaf_<i>`` arrays, no descriptor)."""
    data = np.load(path)
    if _META_KEY not in data.files:
        # legacy format 1: dtypes were native, arrays stored direct
        n = len([k for k in data.files if k.startswith("leaf_")])
        return [data[f"leaf_{i}"] for i in range(n)], {"format": 1,
                                                       "nleaves": n}
    meta = json.loads(bytes(data[_META_KEY].tobytes()).decode())
    leaves = []
    for i, desc in enumerate(meta["leaves"]):
        raw = data[f"leaf_{i}"]
        arr = raw.view(_resolve_dtype(desc["dtype"])).reshape(desc["shape"])
        leaves.append(arr)
    return leaves, meta


def _resolve_dtype(name: str) -> np.dtype:
    """Dtype from its recorded name.  bf16 (and friends) only exist in
    numpy's registry after ml_dtypes is imported — a jax process has it
    implicitly, the jax-free recovery path must pull it in itself."""
    try:
        return np.dtype(name)
    except TypeError:
        try:
            import ml_dtypes  # noqa: F401  (registers the dtypes)

            return np.dtype(name)
        except (ImportError, TypeError):
            raise TypeError(
                f"checkpoint leaf dtype {name!r} is not resolvable in "
                "this process (for bfloat16 and friends, install "
                "ml_dtypes)")


def _check_match(path: str, like_leaves, loaded_leaves) -> None:
    """Loud, specific mismatch errors: a silent zip would truncate."""
    if len(like_leaves) != len(loaded_leaves):
        raise ValueError(
            f"checkpoint {path} holds {len(loaded_leaves)} leaves but "
            f"the provided tree has {len(like_leaves)} — the model "
            "architecture (or optimizer state shape) changed since the "
            "checkpoint was written")
    for i, (want, got) in enumerate(zip(like_leaves, loaded_leaves)):
        w = np.asarray(want)
        if tuple(w.shape) != tuple(got.shape):
            raise ValueError(
                f"checkpoint {path} leaf {i} has shape "
                f"{tuple(got.shape)} but the provided tree expects "
                f"{tuple(w.shape)}")


# ---------------- single-file API ----------------


def save(path: str, tree: Any) -> None:
    """Save a pytree of arrays to ``path`` (directory for orbax, file
    for the npz fallback).  Atomic either way: the npz path writes
    tmp + ``os.replace`` — a crash mid-save leaves any previous file at
    ``path`` untouched."""
    ocp = _try_orbax()
    if ocp is not None and not path.endswith(".npz"):
        ckptr = ocp.PyTreeCheckpointer()
        leaves, treedef = _flatten(tree)
        ckptr.save(os.path.abspath(path),
                   _unflatten(treedef, [np.asarray(x) for x in leaves]))
        return
    _write_npz(path if path.endswith(".npz") else path + ".npz", tree)


def restore(path: str, like: Any) -> Any:
    """Restore a pytree saved by :func:`save`; ``like`` supplies the
    structure (and is required for the npz fallback).  Raises
    ``ValueError`` with the exact mismatch when ``like`` does not match
    what the checkpoint holds."""
    ocp = _try_orbax()
    if ocp is not None and os.path.isdir(path):
        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(os.path.abspath(path))
        import jax

        leaves = jax.tree.leaves(restored)
        like_leaves, treedef = _flatten(like)
        _check_match(path, like_leaves, [np.asarray(x) for x in leaves])
        return _unflatten(treedef, leaves)
    if not path.endswith(".npz"):
        path = path + ".npz"
    leaves, _ = _read_npz(path)
    like_leaves, treedef = _flatten(like)
    _check_match(path, like_leaves, leaves)
    return _unflatten(treedef, leaves)


# ---------------- sharded, committed, generation-stamped ----------------


def step_dir(directory: str, step: int) -> str:
    return os.path.join(directory, f"step_{int(step):08d}")


def _shard_path(d: str, rank: int, nshards: int) -> str:
    return os.path.join(d, f"shard{int(rank)}of{int(nshards)}.npz")


def committed_steps(directory: str):
    """Steps with a committed manifest, ascending.  Manifest-less step
    directories (a save interrupted mid-flight) are invisible here by
    design — that is the torn-checkpoint guarantee."""
    steps = []
    try:
        names = os.listdir(directory)
    except OSError:
        return steps
    for name in names:
        if not name.startswith("step_"):
            continue
        if not os.path.exists(os.path.join(directory, name, MANIFEST)):
            continue
        try:
            steps.append(int(name[len("step_"):]))
        except ValueError:
            continue
    return sorted(steps)


def latest_step(directory: str):
    """Newest committed step in ``directory``, or None."""
    steps = committed_steps(directory)
    return steps[-1] if steps else None


def _resolve_dir(directory):
    directory = directory or config.ckpt_dir()
    if not directory:
        raise ValueError(
            "no checkpoint directory: pass directory= or set "
            "MPI4JAX_TPU_CKPT_DIR")
    return directory


def _comm_coords(comm):
    if comm is None:
        return 0, 1
    return int(comm.rank()), int(comm.size())


def _barrier(comm) -> None:
    if comm is None or comm.size() <= 1:
        return
    from ..runtime import bridge

    bridge.barrier(comm.handle)


def save_sharded(tree: Any, *, step: int, directory: Optional[str] = None,
                 comm=None, generation: Optional[int] = None,
                 replicated: bool = True, keep: Optional[int] = None,
                 _crash_point: Optional[str] = None) -> str:
    """Write one committed checkpoint for ``step``; returns its
    directory.  Collective over ``comm`` (None = single process).

    Commit protocol (the torn-checkpoint guarantee): every rank writes
    its shard atomically (tmp + rename) into the step directory, a
    barrier confirms all shards are durable, THEN rank 0 atomically
    writes ``manifest.json`` — the commit point — and a second barrier
    releases the others.  A kill anywhere in between leaves a
    manifest-less directory that readers ignore; re-saving the same
    step later simply overwrites it.

    ``replicated`` records that every rank's tree is identical (the DP
    pattern); only such checkpoints can restore onto a DIFFERENT world
    size after elastic recovery.  ``generation`` stamps the world
    generation (default: the live elastic generation).  ``keep`` prunes
    all but the newest ``keep`` committed steps after the commit.

    ``_crash_point`` is a test seam for the kill-during-save suite:
    ``"after_shard"`` dies before the manifest exists, ``"mid_commit"``
    dies after the manifest tmp file is written but before the rename.
    """
    directory = _resolve_dir(directory)
    rank, nshards = _comm_coords(comm)
    if generation is None:
        # the live generation: recover() mirrors every successful
        # recovery into MPI4JAX_TPU_GENERATION, so the env read needs
        # no import of the elastic package (which imports this module)
        generation = config.generation()
    d = step_dir(directory, step)
    os.makedirs(d, exist_ok=True)
    _write_npz(_shard_path(d, rank, nshards), tree,
               {"step": int(step), "rank": rank, "nshards": nshards,
                "generation": int(generation)})
    if _crash_point == "after_shard":
        os._exit(137)
    _barrier(comm)
    if rank == 0:
        manifest = {
            "version": 1,
            "step": int(step),
            "generation": int(generation),
            "nshards": nshards,
            "replicated": bool(replicated),
            "shards": [os.path.basename(_shard_path(d, r, nshards))
                       for r in range(nshards)],
        }
        tmp = os.path.join(d, f"{MANIFEST}.tmp.{os.getpid()}")
        with open(tmp, "w") as f:
            json.dump(manifest, f)
            f.flush()
            os.fsync(f.fileno())
        if _crash_point == "mid_commit":
            os._exit(137)
        os.replace(tmp, os.path.join(d, MANIFEST))
    _barrier(comm)
    if keep is not None and rank == 0:
        import shutil

        for old in committed_steps(directory)[:-max(int(keep), 1)]:
            shutil.rmtree(step_dir(directory, old), ignore_errors=True)
    return d


def restore_sharded(like: Any, *, directory: Optional[str] = None,
                    step: Optional[int] = None, comm=None):
    """Restore the newest committed checkpoint (or ``step``); returns
    ``(tree, step, manifest)``.  Raises ``FileNotFoundError`` when no
    committed checkpoint exists.

    A rank reads its own shard when the world size matches the
    checkpoint; after a shrink (or any size change) only
    ``replicated`` checkpoints are accepted — every shard holds the
    same tree, so rank r reads shard ``min(r, nshards-1)``.  A
    non-replicated (truly sharded) state cannot be resharded here and
    raises with that explanation.
    """
    directory = _resolve_dir(directory)
    if step is None:
        step = latest_step(directory)
        if step is None:
            raise FileNotFoundError(
                f"no committed checkpoint under {directory} (a directory "
                "without manifest.json is an interrupted save)")
    d = step_dir(directory, step)
    with open(os.path.join(d, MANIFEST)) as f:
        manifest = json.load(f)
    nshards = int(manifest["nshards"])
    rank, size = _comm_coords(comm)
    if size == nshards:
        shard = rank
    elif manifest.get("replicated", False):
        shard = min(rank, nshards - 1)
    else:
        raise ValueError(
            f"checkpoint {d} holds {nshards} non-replicated shards but "
            f"the world now has {size} ranks — resharding is not "
            "supported; save replicated=True state (the DP pattern) to "
            "survive elastic world-size changes")
    path = _shard_path(d, shard, nshards)
    leaves, _ = _read_npz(path)
    like_leaves, treedef = _flatten(like)
    _check_match(path, like_leaves, leaves)
    return _unflatten(treedef, leaves), int(step), manifest
