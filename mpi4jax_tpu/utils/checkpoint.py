"""Checkpoint / resume for model state pytrees.

The reference has no checkpointing at all (SURVEY.md §5.4); training
frameworks need it, so this framework ships a minimal, dependency-light
implementation: orbax when available, otherwise a flattened ``.npz`` with a
structure descriptor.  Works for any pytree of arrays (params, optimizer
state, solver state).

Single-controller semantics: arrays are fetched to host (global views of
sharded arrays) and restored with whatever sharding the consumer applies;
for multi-process (world-tier) jobs, call on rank 0 after a ``gather`` or
give each rank its own path.
"""

from __future__ import annotations

import os
from typing import Any

import numpy as np

import jax


def _try_orbax():
    try:
        import orbax.checkpoint as ocp  # type: ignore

        return ocp
    except Exception:
        return None


def save(path: str, tree: Any) -> None:
    """Save a pytree of arrays to ``path`` (directory for orbax, file for
    npz fallback)."""
    ocp = _try_orbax()
    if ocp is not None and not path.endswith(".npz"):
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.abspath(path), jax.tree.map(np.asarray, tree))
        return
    leaves, _ = jax.tree.flatten(tree)
    arrays = {f"leaf_{i}": np.asarray(x) for i, x in enumerate(leaves)}
    np.savez(path if path.endswith(".npz") else path + ".npz", **arrays)


def restore(path: str, like: Any) -> Any:
    """Restore a pytree saved by :func:`save`; ``like`` supplies the
    structure (and is required for the npz fallback)."""
    ocp = _try_orbax()
    if ocp is not None and os.path.isdir(path):
        ckptr = ocp.PyTreeCheckpointer()
        restored = ckptr.restore(os.path.abspath(path))
        # reattach the caller's pytree structure (orbax returns nested dicts)
        leaves = jax.tree.leaves(restored)
        return jax.tree.unflatten(jax.tree.structure(like), leaves)
    if not path.endswith(".npz"):
        path = path + ".npz"
    data = np.load(path)
    n = len([k for k in data.files if k.startswith("leaf_")])
    leaves = [data[f"leaf_{i}"] for i in range(n)]
    return jax.tree.unflatten(jax.tree.structure(like), leaves)
