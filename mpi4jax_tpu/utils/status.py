"""Receive-status introspection (the MPI_Status analog).

The reference threads a user-supplied ``MPI.Status`` pointer through the
custom call and lets libmpi fill it at run time
(/root/reference/mpi4jax/_src/collective_ops/recv.py:120-123,
mpi_xla_bridge.pyx:23-27 there, tested in
tests/collective_ops/test_sendrecv.py:29-61).  Here the same contract is
kept — a mutable :class:`Status` object passed to ``recv``/``sendrecv``
is filled when the receive executes, eagerly or under ``jit`` — with the
fill performed by the ordered host callback from the native transport's
frame header (source, tag, byte count).

Wildcards: ``ANY_TAG`` is supported (the transport reports the tag that
arrived), and so is ``ANY_SOURCE`` (the reference's default source,
recv.py:45 there): the native transport polls every peer socket and
takes whichever completes a frame first, reporting the actual source
through the Status.  Per-socket order stays strict, so a wildcard
receive composes with — rather than replaces — the ordered-transport
contract.
"""

from __future__ import annotations

import numpy as np

#: Accept a message with any tag (reported via :class:`Status`).
ANY_TAG = -1

#: Accept a message from any peer (first complete frame wins; the actual
#: sender is reported via :class:`Status`).  Matches the reference's
#: ``MPI.ANY_SOURCE`` default for ``recv``.
ANY_SOURCE = -2

#: Value of Status fields before any receive has filled them.
UNDEFINED = -32766


class Status:
    """Mutable record filled by the most recent receive it was passed to.

    Mirrors the ``mpi4py.MPI.Status`` surface the reference tests use:
    ``Get_source`` / ``Get_tag`` / ``Get_count`` / ``Get_elements``.
    """

    __slots__ = ("source", "tag", "count_bytes")

    def __init__(self):
        self.source = UNDEFINED
        self.tag = UNDEFINED
        self.count_bytes = UNDEFINED

    def _fill(self, source: int, tag: int, count_bytes: int) -> None:
        self.source = int(source)
        self.tag = int(tag)
        self.count_bytes = int(count_bytes)

    def Get_source(self) -> int:
        return self.source

    def Get_tag(self) -> int:
        return self.tag

    def Get_count(self, dtype=None) -> int:
        """Received size: bytes, or elements when ``dtype`` is given."""
        if dtype is None:
            return self.count_bytes
        return self.count_bytes // np.dtype(dtype).itemsize

    # mpi4py spells element counts Get_elements(datatype)
    Get_elements = Get_count

    def __repr__(self):
        return (
            f"Status(source={self.source}, tag={self.tag}, "
            f"count_bytes={self.count_bytes})"
        )


class HashableStatus:
    """Wrap a Status as a hashable static primitive param.

    Keyed on object identity, like the reference's pointer-keyed
    ``HashableMPIType`` (utils.py:133-152 there): rebinding with a new
    Status object retraces, rebinding with the same one hits the cache.
    """

    __slots__ = ("obj",)

    def __init__(self, obj: Status):
        self.obj = obj

    def __hash__(self):
        return hash(("mpi4jax_tpu.Status", id(self.obj)))

    def __eq__(self, other):
        return isinstance(other, HashableStatus) and other.obj is self.obj
