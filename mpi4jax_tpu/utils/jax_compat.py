"""JAX version handling.

The reference pins a "latest known good" jax and warns beyond it
(/root/reference/mpi4jax/_src/jax_compat.py:24-47).  We do the same with a
much smaller surface: this framework targets jax >= 0.9 (no pre-0.5 shims —
the reference needed them for jax 0.4.x, we do not).
"""

from __future__ import annotations

import warnings

from . import config

MIN_JAX_VERSION = (0, 6, 0)
LATEST_TESTED_JAX_VERSION = (0, 9, 0)


def _parse(version: str) -> tuple:
    parts = []
    for piece in version.split(".")[:3]:
        digits = "".join(ch for ch in piece if ch.isdigit())
        parts.append(int(digits or 0))
    return tuple(parts)


def check_jax_version() -> None:
    import jax

    found = _parse(jax.__version__)
    if found < MIN_JAX_VERSION:
        raise ImportError(
            f"mpi4jax_tpu requires jax >= {'.'.join(map(str, MIN_JAX_VERSION))}, "
            f"found {jax.__version__}"
        )
    if found > LATEST_TESTED_JAX_VERSION and not config.flag(
        "MPI4JAX_TPU_NO_WARN_JAX_VERSION"
    ):
        warnings.warn(
            f"jax {jax.__version__} is newer than the latest version tested "
            f"with mpi4jax_tpu "
            f"({'.'.join(map(str, LATEST_TESTED_JAX_VERSION))}). "
            "If you encounter problems, pin jax or set "
            "MPI4JAX_TPU_NO_WARN_JAX_VERSION=1 to silence this warning.",
            UserWarning,
        )


def vma_check_mode():
    """Whether shard_map tracks varying-manual-axes (``check_vma=True``).

    Returns True/False, or ``None`` when the probe fails — the switch is
    private jax API (``jax._src.config._check_vma``), and this is the one
    place that reads it, so a future rename is a one-line fix.  On None,
    callers choose their own failure mode: loud where a wrong guess would
    corrupt results (``as_varying``), soft where a fallback is harmless
    (Pallas out-structs).
    """
    try:
        from jax._src import config as _jcfg

        return bool(_jcfg._check_vma.value)
    except Exception:
        return None


def bool_state(**kwargs):
    """``jax._src.config.bool_state`` across jax versions.

    Newer keyword-only flags (``include_in_jit_key``,
    ``include_in_trace_context``) are dropped when the installed jax
    predates them, so modules defining config states stay *importable* on
    older jax — wanted by tooling that runs without compiling anything
    (``mpi4jax_tpu.analysis`` executes eagerly under ``disable_jit``,
    where the jit-cache-key flag is moot).  Production use is still
    gated on MIN_JAX_VERSION by ``check_jax_version``.
    """
    from jax._src import config as _jcfg

    kw = dict(kwargs)
    for _ in range(2):
        try:
            return _jcfg.bool_state(**kw)
        except TypeError as err:
            dropped = False
            for opt in ("include_in_trace_context", "include_in_jit_key"):
                if opt in kw and opt in str(err):
                    kw.pop(opt)
                    dropped = True
                    break
            if not dropped:
                raise
    return _jcfg.bool_state(**kw)
