"""Multi-host (pod-scale) initialization helpers.

The reference scales across hosts through MPI ranks (`mpirun` on every
host).  TPU-native, multi-host scaling is *single-program multi-controller*
JAX: every host runs the same program, `jax.distributed.initialize` wires
the controllers, and one global `Mesh` spans every chip — ICI inside a
slice, DCN between slices — with the same `spmd`/collective code as
single-host (the compiler routes collectives over the right fabric).

    # on every host of the pod (or let TPU metadata fill the arguments)
    import mpi4jax_tpu as m4j
    m4j.runtime.distributed.initialize()       # jax.distributed under the hood
    mesh = m4j.make_mesh()                     # spans ALL hosts' devices
    out = m4j.spmd(fn, mesh=mesh)(global_array)

The world tier composes with this for MPMD patterns: set
``MPI4JAX_TPU_HOSTS`` to the per-rank host list and launch one rank per
host; world ops then stage through the native transport over DCN while
mesh ops stay on ICI (SURVEY.md §5.8's two-tier design).
"""

from __future__ import annotations

from typing import Optional


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    **kwargs,
) -> None:
    """Initialize multi-controller JAX (no-op when already initialized or
    single-process).  Arguments default to TPU-pod auto-detection."""
    import jax

    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
            **kwargs,
        )
    except RuntimeError as err:  # already initialized
        if "already" not in str(err).lower():
            raise


def global_mesh(axis: str = "mpi"):
    """A 1-D mesh over every device of every host (call after
    :func:`initialize`)."""
    from ..parallel.mesh import make_mesh

    return make_mesh(axis=axis)
