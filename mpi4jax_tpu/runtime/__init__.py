from . import transport  # noqa: F401
