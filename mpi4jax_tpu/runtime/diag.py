"""Layer-by-layer environment diagnostics.

    python -m mpi4jax_tpu.runtime.diag [--device] [--json]

Checks, in dependency order, each seam a job can fail on — native
build, transport loopback, launcher, and (with ``--device``) the
accelerator claim / compile / host-callback capabilities — and prints
one PASS/FAIL line per check (or one JSON object with ``--json``).
The reference has no analog; its failure modes surface as mpirun
aborts.  Device checks run in subprocesses with timeouts so a wedged
device claim (docs/developers.md: the axon tunnel holds a dead
claimer's claim for many minutes) is reported, not inherited.

Exit code: number of failed checks.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _run_snippet(code: str, timeout: int, env_extra=None):
    env = dict(os.environ)
    env.setdefault("PYTHONPATH", REPO)
    if env_extra:
        env.update(env_extra)
    try:
        res = subprocess.run(
            [sys.executable, "-c", code], capture_output=True, text=True,
            timeout=timeout, env=env,
        )
        return res.returncode, res.stdout, res.stderr
    except subprocess.TimeoutExpired as err:
        return None, err.stdout or "", err.stderr or ""


def check_native_build():
    """The C++ transport builds/loads and reports its symbols."""
    from . import bridge

    lib = bridge.get_lib()
    missing = [s for s in ("tpucomm_init", "tpucomm_allreduce",
                           "tpucomm_sendrecv", "tpucomm_split")
               if not hasattr(lib, s)]
    return not missing, f"missing symbols: {missing}" if missing else "loaded"


def check_ffi():
    """XLA FFI handlers are exported (cpu fast path)."""
    from ..utils import config

    if config.ffi_disabled():
        # a deliberate kill switch is a configuration, not a failure —
        # report healthy with the reason (the callback path serves)
        return True, "disabled by MPI4JAX_TPU_DISABLE_FFI (callback path)"
    from . import bridge

    return bridge.ffi_available(), "tpucomm_ffi handlers"


def check_coll_algo_engine():
    """The collective algorithm engine resolves a decision table, and
    the quantized wire formats (qring/qrd) are available and sane: the
    native int8+scales codec round-trips a random payload within the
    per-block error bound (|err| <= blockwise absmax / 127)."""
    import numpy as np

    from .. import tune
    from . import bridge

    info = tune.describe()
    picks = info["picks"]
    detail = " ".join(
        f"{op}@1KB={picks[op]['1KB']} @16MB={picks[op]['16MB']}"
        for op in ("allreduce", "allgather")
    )
    detail += " [" + "+".join(info["sources"]) + "]"
    # the engine must agree with itself: every pick is a real algorithm
    ok = all(
        picks[op][k] in tune.TRACE_ALGOS
        for op in picks for k in picks[op]
    )
    if not bridge.quant_available():
        # a stale prebuilt library keeps every exact collective working
        # (same tolerance as obs: unobserved, not broken) — report the
        # missing capability without failing the check
        return ok, detail + " quant=UNAVAILABLE (native library " \
            "predates the quantized engine; rebuild native/ to enable " \
            "qring/qrd)"
    from ..ops import quantized as q

    # wire-format loopback: pack through the NATIVE codec, unpack,
    # assert the per-block quantization error bound, and cross-check
    # the packed bytes against the documented numpy reference
    rng = np.random.RandomState(3)
    x = (rng.randn(1000) * 5).astype(np.float32)
    packed = bridge.quant_pack(x)
    if packed.size != bridge.quant_packed_bytes(x.size):
        return False, detail + " quant packed-size mismatch"
    scales, codes = q.quant_pack_ref(x)
    ref = np.concatenate([scales.view(np.int8), codes])
    if not np.array_equal(packed, ref):
        return False, detail + " quant codec diverges from the " \
            "documented reference (native vs quant_pack_ref)"
    back = bridge.quant_unpack(packed, x.size, np.float32)
    nb = (x.size + q.QUANT_BLOCK - 1) // q.QUANT_BLOCK
    for b in range(nb):
        blk = slice(b * q.QUANT_BLOCK, min(x.size, (b + 1) * q.QUANT_BLOCK))
        bound = np.max(np.abs(x[blk])) / 127.0 * 0.5 + 1e-9
        if np.max(np.abs(back[blk] - x[blk])) > bound:
            return False, detail + f" quant error bound violated in " \
                f"block {b}"
    ratio = x.nbytes / packed.nbytes
    detail += f" quant=qring,qrd (codec round-trip ok, {ratio:.2f}x wire)"
    # the alltoall family (MoE expert exchange): the typed engine entry
    # is what makes qalltoall/halltoall/hqalltoall resolvable; the
    # quantized members additionally need the codec probed above
    if hasattr(bridge.get_lib(), "tpucomm_alltoall_algo"):
        fam = sorted(tune.A2A_ALGOS)
        ok = ok and all(
            tune._check_algo(a, "alltoall") == a for a in fam)
        detail += " alltoall=" + ",".join(fam)
        detail += (f" (default@1KB={tune.get_algorithm('alltoall', 1024)}"
                   f" @16MB={tune.get_algorithm('alltoall', 16 << 20)})")
    else:
        detail += " alltoall=EXACT-ONLY (library predates the typed " \
            "alltoall engine entry; rebuild native/)"
    return ok, detail


def check_observability(port):
    """The structured recorder end to end, no sockets: a size-1 native
    comm records loopback ops into the event ring, the recording shows
    them in ``obs.stats()``, and the exported trace validates against
    the Chrome trace-event schema."""
    import ctypes

    import numpy as np

    from .. import obs
    from ..obs import _native
    from . import bridge

    lib = bridge.get_lib()
    if not _native.available(lib):
        return False, ("native library predates the event ring "
                       "(no tpucomm_obs_enable); rebuild native/")
    h = lib.tpucomm_init(0, 1, int(port), b"")
    if h == 0:
        return False, "size-1 comm init failed"
    try:
        obs.start(lib=lib, rank=0, size=1)
        x = np.arange(16.0)
        bridge.send(h, x, 0, 7)           # self-delivery loopback
        got = bridge.recv(h, x.shape, x.dtype, 0, 7)
        if not np.allclose(got, x):
            return False, "loopback payload mismatch"
        bridge.allreduce(h, x, 0)
        stats = obs.stats()
        ops = {row["op"] for row in stats["per_op"]}
        if not {"Send", "Recv", "Allreduce"} <= ops:
            return False, f"recorded ops {sorted(ops)} missing Send/Recv/" \
                          "Allreduce"
        # every native row must carry the dispatch-phase split (the
        # async progress engine's queue-time vs wire-time attribution)
        native_rows = [r for r in stats["per_op"] if r["src"] == "native"]
        if not native_rows or any("dispatch_frac" not in r
                                  for r in native_rows):
            return False, "native stats rows missing dispatch_frac"
        count = sum(row["count"] for row in stats["per_op"])
        trace = obs.merge_parts([{
            "rank": 0, "size": 1, "events": obs.events(),
            "dropped": obs.dropped(),
        }])
        errors = obs.validate_chrome_trace(trace)
        if errors:
            return False, f"trace schema errors: {errors[:3]}"
        spans = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        if not any("dispatch_us" in (e.get("args") or {}) for e in spans):
            return False, "trace spans missing dispatch_us"
        from ..utils import config as _config

        engine = ("on" if _config.progress_thread_enabled() else "off")
        return True, (f"{count} loopback events recorded, stats ops "
                      f"{sorted(ops)}, dispatch split present, trace "
                      f"validates ({obs.default_capacity_events()}-event "
                      f"ring; progress engine {engine}, coalesce "
                      f"{_config.coalesce_bytes()} B)")
    finally:
        obs.stop()
        lib.tpucomm_finalize(ctypes.c_int64(h))


def check_transport_loopback(port):
    """2-rank world job over the real launcher + TCP transport."""
    import tempfile

    code = (
        "import sys; sys.path.insert(0, %r)\n"
        # pin in-process: some plugins (axon) ignore the env var and
        # grab the accelerator anyway
        "import jax; jax.config.update('jax_platforms', 'cpu')\n"
        "import mpi4jax_tpu as m\n"
        "import numpy as np, jax.numpy as jnp\n"
        "c = m.get_default_comm()\n"
        "out = m.allreduce(jnp.arange(4.0), op=m.SUM, comm=c)\n"
        "assert np.allclose(np.asarray(out), np.arange(4.0) * 2), out\n"
        "got = m.sendrecv(jnp.arange(3.0) + c.rank(), shift=1, comm=c)\n"
        "assert np.allclose(np.asarray(got), np.arange(3.0) + 1 - c.rank())\n"
        "from mpi4jax_tpu.runtime import bridge\n"
        "act, slot, ring = bridge.shm_info(c.handle)\n"
        # the transport-floor state: on / off / unavailable(<reason>);
        # a pre-uring .so (no status symbol) reads as unavailable, never
        # as a misparsed guess
        "us = bridge.uring_status()\n"
        "if us is None:\n"
        "    us = 'unavailable(native library predates the uring backend)'\n"
        "print('loopback-ok shm=%%d ring_kb=%%d algo16mb=%%s uring=%%s' %% "
        "(act, ring // 1024, c.coll_algo('allreduce', 16 << 20), us))\n"
        % REPO
    )
    with tempfile.NamedTemporaryFile(
        "w", suffix="_m4j_diag.py", delete=False
    ) as f:
        f.write(code)
        prog = f.name
    try:
        res = subprocess.run(
            [sys.executable, "-m", "mpi4jax_tpu.runtime.launch", "-n", "2",
             "--port", str(port), prog],
            capture_output=True, text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"}, cwd=REPO,
        )
        rc, out, err = res.returncode, res.stdout, res.stderr
    except subprocess.TimeoutExpired:
        return False, "timed out (deadlock or port conflict?)"
    finally:
        os.unlink(prog)
    ok = rc == 0 and out.count("loopback-ok") == 2
    if not ok:
        return False, (err.strip() or out)[-200:]
    detail = "2-rank allreduce+sendrecv"
    for line in out.splitlines():
        if line.startswith("loopback-ok"):
            detail += " [" + line[len("loopback-ok "):] + "]"
            break
    return True, detail


def check_failure_detection(port):
    """Transport deadlines + teardown: a deterministically hung rank is
    detected within the configured deadline on a loopback pair, and the
    resolved timeout knobs are reported."""
    import tempfile

    from ..utils import config

    cfg_t = config.transport_timeout_s()
    cfg_c = config.connect_timeout_s()
    knobs = (f"timeout_s={cfg_t:g}" if cfg_t else "timeout_s=off(0)") \
        + f" connect_s={cfg_c:g}"

    deadline_s = 3.0
    # bridge-level ranks (no jax import): rank 1's first recv hangs via
    # the injector; rank 0's recv from it must trip the deadline and
    # name the stuck peer, and the launcher must reap the hung rank
    code = (
        "import sys; sys.path.insert(0, %r)\n"
        "import numpy as np\n"
        "from mpi4jax_tpu.runtime import bridge, transport\n"
        "c = transport.get_world_comm()\n"
        "h = c.handle\n"
        "if c.rank() == 0:\n"
        "    bridge.send(h, np.arange(4.0), 1, 7)\n"
        "    bridge.recv(h, (4,), np.float64, 1, 7)\n"
        "    print('UNREACHABLE', flush=True)\n"
        "else:\n"
        "    bridge.recv(h, (4,), np.float64, 0, 7)\n"
        % REPO
    )
    with tempfile.NamedTemporaryFile(
        "w", suffix="_m4j_diag_fault.py", delete=False
    ) as f:
        f.write(code)
        prog = f.name
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "MPI4JAX_TPU_TIMEOUT_S": str(deadline_s),
        "MPI4JAX_TPU_DISABLE_SHM": "1",
        "MPI4JAX_TPU_FAULT": "rank=1,point=recv,after=0,action=hang",
    }
    t0 = time.perf_counter()
    # own process group: if detection regresses, killpg reaps the
    # launcher AND its (deliberately hung-forever) ranks — a plain
    # subprocess.run timeout would SIGKILL only the launcher and leak
    # the injected hang as a permanent orphan
    import signal as _signal

    proc = subprocess.Popen(
        [sys.executable, "-m", "mpi4jax_tpu.runtime.launch", "-n", "2",
         "--port", str(port), prog],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=REPO, start_new_session=True,
    )
    try:
        out, err = proc.communicate(timeout=60)
    except subprocess.TimeoutExpired:
        try:
            os.killpg(proc.pid, _signal.SIGKILL)
        except (OSError, ProcessLookupError):
            proc.kill()
        proc.communicate()
        return False, (f"{knobs}; injected recv-hang NOT detected "
                       f"within 60 s (deadline {deadline_s:g} s)")
    finally:
        os.unlink(prog)
    dt = time.perf_counter() - t0
    detected = (
        proc.returncode != 0
        and "UNREACHABLE" not in out
        and "timed out" in err
        and "from 1" in err  # the stuck peer is named
    )
    if not detected:
        return False, (knobs + "; " + (err.strip() or out.strip())[-180:])
    return True, (f"{knobs}; injected recv-hang detected in {dt:.1f}s "
                  f"({deadline_s:g}s deadline, stuck peer named)")


def check_self_healing(port):
    """The link-layer self-healing path end to end on a loopback 2-rank
    job: a transient ``reset`` is injected mid-run (MPI4JAX_TPU_FAULT),
    the armed link layer (MPI4JAX_TPU_RETRY) reconnects within ONE
    backoff window (the recovery line says ``[attempt 1/...]``),
    deliberate replay overlap (RETRY_REPLAY_SLACK) proves the seq dedup
    actually drops duplicates, both ranks finish with bit-identical
    digests, and the reconnect + dup-dropped counters surface through
    ``obs.stats()['self_healing']``."""
    import re
    import tempfile

    from ..utils import config
    from . import bridge

    if not hasattr(bridge.get_lib(), "tpucomm_link_counters"):
        return True, ("UNAVAILABLE: native library predates the "
                      "self-healing link layer (no tpucomm_link_counters); "
                      "rebuild native/ to enable it")
    backoff_ms = 100.0
    knobs = (f"retry={config.retry_budget() or 4} "
             f"backoff_ms={backoff_ms:g} crc={config.wire_crc_mode()}")
    code = (
        "import sys, types, os; sys.path.insert(0, %r)\n"
        # parent-package shim: bridge-level ranks must work even where
        # the package's jax gate blocks the full import
        "pkg = types.ModuleType('mpi4jax_tpu')\n"
        "pkg.__path__ = [os.path.join(%r, 'mpi4jax_tpu')]\n"
        "sys.modules['mpi4jax_tpu'] = pkg\n"
        "import numpy as np\n"
        "from mpi4jax_tpu import obs\n"
        "from mpi4jax_tpu.runtime import bridge, transport\n"
        "c = transport.get_world_comm()\n"
        "h = c.handle\n"
        "obs.start(lib=bridge.get_lib(), rank=c.rank(), size=c.size())\n"
        "x = np.arange(256.0) + c.rank()\n"
        "digest = 0.0\n"
        "for it in range(12):\n"
        "    if c.rank() == 0:\n"
        "        bridge.send(h, x, 1, it)\n"
        "        got = bridge.recv(h, x.shape, x.dtype, 1, it)\n"
        "    else:\n"
        "        got = bridge.recv(h, x.shape, x.dtype, 0, it)\n"
        "        bridge.send(h, x, 0, it)\n"
        "    assert np.allclose(got, np.arange(256.0) + (1 - c.rank()))\n"
        "    out = bridge.allreduce(h, x, 2)\n"
        "    digest += float(out.sum())\n"
        "sh = obs.stats().get('self_healing', {})\n"
        # one write() so the two ranks' report lines can't interleave
        # in the launcher's stdout pump
        "sys.stdout.write('diag_heal %%d %%r %%d %%d\\n' %% (\n"
        "    c.rank(), digest,\n"
        "    sh.get('reconnects', 0), sh.get('dup_dropped', 0)))\n"
        "sys.stdout.flush()\n"
        % (REPO, REPO)
    )
    with tempfile.NamedTemporaryFile(
        "w", suffix="_m4j_diag_heal.py", delete=False
    ) as f:
        f.write(code)
        prog = f.name
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "MPI4JAX_TPU_DISABLE_SHM": "1",
        "MPI4JAX_TPU_TIMEOUT_S": "30",
        "MPI4JAX_TPU_RETRY": "4",
        "MPI4JAX_TPU_RETRY_BACKOFF_MS": f"{backoff_ms:g}",
        # deliberate replay overlap: the receiver must DROP the
        # duplicates, proving the seq dedup (not just the reconnect)
        "MPI4JAX_TPU_RETRY_REPLAY_SLACK": "1",
        "MPI4JAX_TPU_FAULT": "rank=0,point=send,after=5,action=reset",
    }
    t0 = time.perf_counter()
    try:
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py"),
             "-n", "2", "--port", str(port), prog],
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return False, f"{knobs}; healing run hung (no reconnect?)"
    finally:
        os.unlink(prog)
    dt = time.perf_counter() - t0
    lines = {
        int(m.group(1)): (m.group(2), int(m.group(3)), int(m.group(4)))
        for m in re.finditer(
            r"diag_heal (\d+) (\S+) (\d+) (\d+)", res.stdout)
    }
    # recovery within one backoff window == the link came back on the
    # FIRST reconnect attempt (each later attempt waits another window)
    first_window = re.search(
        r"self-heal: link to r\d+ recovered .*\[attempt 1/", res.stderr)
    ok = (
        res.returncode == 0
        and len(lines) == 2
        and lines[0][0] == lines[1][0]          # bit-identical digests
        and "fault injection: reset" in res.stderr
        and first_window is not None
        and all(v[1] >= 1 for v in lines.values())   # reconnects in stats
        and any(v[2] >= 1 for v in lines.values())   # dups dropped in stats
        and "healed in-place" in res.stderr     # launcher post-mortem
    )
    if not ok:
        tail = (res.stderr.strip() or res.stdout.strip())[-220:]
        return False, f"{knobs}; healing run failed: {tail}"
    return True, (f"{knobs}; injected link reset healed on attempt 1 "
                  f"(one backoff window), digests bit-identical, "
                  f"reconnects={lines[0][1]}+{lines[1][1]} "
                  f"dup_dropped={lines[0][2]}+{lines[1][2]} via "
                  f"obs.stats() in {dt:.1f}s")


def check_elasticity(port):
    """Elastic recovery end to end on a loopback 3-rank job: rank 1 is
    deterministically killed mid-run (MPI4JAX_TPU_FAULT), the survivors
    shrink to np=2 through the launcher's generation protocol and the
    native tpucomm_shrink bootstrap, resume from the last committed
    checkpoint, and the job exits 0 with bit-identical results."""
    import tempfile

    from ..utils import config
    from . import bridge

    if not bridge.shrink_available():
        return True, ("UNAVAILABLE: native library predates elastic "
                      "recovery (no tpucomm_shrink); rebuild native/ "
                      "to enable it")
    knobs = (f"policy={config.elastic_policy()} "
             f"grace_s={config.elastic_grace_s():g}")
    code = (
        "import sys, types, os; sys.path.insert(0, %r)\n"
        "pkg = types.ModuleType('mpi4jax_tpu')\n"
        "pkg.__path__ = [os.path.join(%r, 'mpi4jax_tpu')]\n"
        "sys.modules['mpi4jax_tpu'] = pkg\n"
        "import hashlib\n"
        "import numpy as np\n"
        "from mpi4jax_tpu.elastic import training\n"
        "from mpi4jax_tpu.runtime import bridge, transport\n"
        "def step_fn(state, step, comm):\n"
        "    g = bridge.allreduce(comm.handle,\n"
        "                         np.cos(np.arange(8) * (step + 1)), 2)\n"
        "    return state - 0.1 * g\n"
        "comm = transport.get_world_comm()\n"
        "state = training.run(step_fn, np.zeros(8), steps=8,\n"
        "                     save_every=2)\n"
        "d = hashlib.sha256(state.tobytes()).hexdigest()[:16]\n"
        "print('diag_elastic digest', d, flush=True)\n"
        % (REPO, REPO)
    )
    with tempfile.TemporaryDirectory(prefix="m4j_diag_elastic_") as td:
        prog = os.path.join(td, "prog.py")
        with open(prog, "w") as f:
            f.write(code)
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "MPI4JAX_TPU_DISABLE_SHM": "1",
            "MPI4JAX_TPU_TIMEOUT_S": "6",
            "MPI4JAX_TPU_CKPT_DIR": os.path.join(td, "ckpt"),
            "MPI4JAX_TPU_FAULT": "rank=1,point=send,after=10,action=exit",
        }
        t0 = time.perf_counter()
        # the launcher runs as a FILE (not -m): the rank program uses
        # the parent-package shim so the whole check works even where
        # the package's jax gate blocks imports, and -m would defeat
        # that by importing the package in the launcher process
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py"),
             "-n", "3", "--port", str(port), "--elastic", prog],
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
        )
    dt = time.perf_counter() - t0
    import re

    digests = set(re.findall(r"diag_elastic digest (\w+)", res.stdout))
    ok = (
        res.returncode == 0
        and "completed after recovery" in res.stderr
        and "generation 1" in res.stderr
        and len(digests) == 1  # both survivors, identical state
        and res.stdout.count("diag_elastic digest") == 2
    )
    if not ok:
        tail = (res.stderr.strip() or res.stdout.strip())[-220:]
        return False, f"{knobs}; recovery run failed: {tail}"
    return True, (f"{knobs}; injected rank death recovered np=3->np=2 "
                  f"in {dt:.1f}s (exit 0, survivors bit-identical, "
                  "resume from committed checkpoint)")


def check_serving(port):
    """Serving v2 end to end on a loopback 3-rank job under forced
    disaggregation (docs/serving.md): roles derive to frontend=r0 /
    prefill=r1 / decode=r2, one request is prefilled on rank 1, its KV
    shipped to rank 2 and decoded there, the KV wire bytes show up in
    each worker's ``obs.stats()`` tier rows, and a second submit over
    the queue cap is shed with a loud verdict instead of admitted."""
    import tempfile

    from ..utils import config

    knobs = (f"roles={config.serve_roles()} "
             f"max_batch={config.serve_max_batch()} "
             f"queue_cap={config.serve_queue_cap()} "
             f"slo_ms={config.serve_slo_ms():g}")
    code = (
        "import sys, types, os; sys.path.insert(0, %r)\n"
        "pkg = types.ModuleType('mpi4jax_tpu')\n"
        "pkg.__path__ = [os.path.join(%r, 'mpi4jax_tpu')]\n"
        "sys.modules['mpi4jax_tpu'] = pkg\n"
        "from mpi4jax_tpu import obs, serving\n"
        "from mpi4jax_tpu.runtime import transport\n"
        "comm = transport.get_world_comm()\n"
        "_ = comm.handle\n"
        "obs.start(rank=comm.rank(), size=comm.size())\n"
        "adapter = serving.ToyAdapter()\n"
        "if comm.rank() != 0:\n"
        "    roles = serving.serve_worker(comm, adapter,\n"
        "                                 roles_mode='disagg')\n"
        "    st = obs.stats()\n"
        "    kv = st.get('tier_bytes', {}).get('kv', 0)\n"
        "    phases = sorted({r['phase'] for r in st['per_op']\n"
        "                     if 'phase' in r})\n"
        "    msg = ' '.join(['diag_serving worker', str(comm.rank()),\n"
        "                    roles.role_of(comm.rank()), str(kv),\n"
        "                    ','.join(phases)])\n"
        "    sys.stdout.write(msg + chr(10)); sys.stdout.flush()\n"
        "else:\n"
        "    server = serving.Server(comm, adapter, max_batch=2,\n"
        "                            chunk_tokens=4, queue_cap=1,\n"
        "                            roles_mode='disagg')\n"
        "    ok_v = server.submit([3, 1, 4, 1, 5], max_new=4)\n"
        "    assert ok_v.admitted, ok_v.reason\n"
        "    shed_v = server.submit([2, 7], max_new=4)\n"
        "    assert not shed_v.admitted, 'over-cap submit was admitted'\n"
        "    server.run_until_drained()\n"
        "    server.stop()\n"
        "    req = server.completed[0]\n"
        "    msg = ' '.join(['diag_serving frontend', server.roles.mode,\n"
        "                    str(len(server.completed)),\n"
        "                    str(len(req.generated)),\n"
        "                    str(server.admission.shed), shed_v.reason])\n"
        "    sys.stdout.write(msg + chr(10)); sys.stdout.flush()\n"
        % (REPO, REPO)
    )
    with tempfile.TemporaryDirectory(prefix="m4j_diag_serving_") as td:
        prog = os.path.join(td, "prog.py")
        with open(prog, "w") as f:
            f.write(code)
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "MPI4JAX_TPU_DISABLE_SHM": "1",
            "MPI4JAX_TPU_TIMEOUT_S": "8",
        }
        t0 = time.perf_counter()
        # launcher as a FILE (not -m) for the same reason as
        # check_elasticity: the rank program's parent-package shim must
        # survive environments where the package's jax gate blocks
        # imports
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py"),
             "-n", "3", "--port", str(port), prog],
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
        )
    dt = time.perf_counter() - t0
    import re

    fe = re.search(r"diag_serving frontend (\S+) (\d+) (\d+) (\d+) (.+)",
                   res.stdout)
    workers = {
        int(m.group(1)): (m.group(2), int(m.group(3)), m.group(4))
        for m in re.finditer(
            r"diag_serving worker (\d+) (\S+) (\d+) (\S*)", res.stdout)
    }
    ok = (
        res.returncode == 0
        and fe is not None
        and fe.group(1) == "disagg"
        and int(fe.group(2)) == 1          # the admitted request drained
        and int(fe.group(3)) == 4          # all 4 tokens generated
        and int(fe.group(4)) >= 1          # the over-cap submit was shed
        and "capacity" in fe.group(5)
        and "SHED" in res.stderr           # ... loudly
        and workers.get(1, ("", 0, ""))[0] == "prefill"
        and workers.get(2, ("", 0, ""))[0] == "decode"
        and workers[1][1] > 0 and workers[2][1] > 0  # KV bytes in stats
        and "prefill" in workers[1][2]
        and "kv_xfer" in workers[1][2]
        and "decode" in workers[2][2]
        and "kv_xfer" in workers[2][2]
    )
    if not ok:
        tail = (res.stderr.strip() or res.stdout.strip())[-220:]
        return False, f"{knobs}; serving run failed: {tail}"
    return True, (f"{knobs}; np=3 disagg roles prefill=r1 decode=r2, "
                  f"request prefilled r1 -> KV {workers[1][1]} B shipped "
                  f"-> decoded r2, kv tier bytes in both workers' stats, "
                  f"over-cap submit shed loudly in {dt:.1f}s")


def check_live_retune(port):
    """The live re-tuning brain end to end on a loopback 2-rank job:
    drift is forced by pointing ``MPI4JAX_TPU_TUNE_MODEL`` at a synthetic
    cost model that predicts the pinned ``ring`` algorithm absurdly fast
    (so real loopback timings drift immediately) while ``rd`` stays
    modest (so the candidate overlay re-picks it), and the check asserts
    the armed controller detects the drift, rank 0 proposes, the epoch
    rendezvous installs the new table on BOTH ranks at the same epoch,
    and the swap report names the old -> new winner."""
    import re
    import tempfile

    from ..utils import config

    window, cooldown = 32, 8
    knobs = (f"window={window} cooldown={cooldown} "
             f"drift_pct=50 quant={config.quant_mode()}")
    model = json.dumps({
        "version": 1, "world_size": 2, "topology": None,
        "dtype": "float32", "knobs": {}, "source": "diag-forced",
        "samples": {
            # ring predicted ~1us at 256 KiB: any real loopback timing
            # drifts; rd modest so the overlaid candidate re-picks it
            "allreduce/ring": {"1024": 1e-7, "262144": 1e-6},
            "allreduce/rd": {"1024": 2e-6, "262144": 5e-6},
        },
        "wire_frac": {}, "dispatch_frac": {},
    })
    code = (
        "import sys, types, os, time; sys.path.insert(0, %r)\n"
        # parent-package shim: bridge-level ranks must work even where
        # the package's jax gate blocks the full import
        "pkg = types.ModuleType('mpi4jax_tpu')\n"
        "pkg.__path__ = [os.path.join(%r, 'mpi4jax_tpu')]\n"
        "sys.modules['mpi4jax_tpu'] = pkg\n"
        "import numpy as np\n"
        "from mpi4jax_tpu import live\n"
        "from mpi4jax_tpu.runtime import bridge, transport\n"
        "c = transport.get_world_comm()\n"
        "h = c.handle\n"
        "assert live.armed(), 'live controller failed to arm'\n"
        "x = np.zeros(65536, dtype=np.float32)\n"  # 256 KiB payload
        "deadline = time.time() + 45\n"
        "ops = 0\n"
        "while time.time() < deadline:\n"
        "    bridge.allreduce(h, x, 0)\n"
        "    ops += 1\n"
        "    if live.status().get('epoch', 0) > 0:\n"
        "        break\n"
        "    time.sleep(0.002)\n"
        "st = live.status()\n"
        "sw = st.get('swaps', [])\n"
        "changes = ';'.join(sw[0]['report'].get('changes', [])) if sw "
        "else ''\n"
        # one write() so the two ranks' report lines can't interleave
        "sys.stdout.write('diag_live %%d epoch %%d ops %%d errors %%d "
        "changes %%r\\n' %% (\n"
        "    c.rank(), st.get('epoch', 0), ops, st.get('errors', -1),\n"
        "    changes))\n"
        "sys.stdout.flush()\n"
        % (REPO, REPO)
    )
    with tempfile.NamedTemporaryFile(
        "w", suffix="_m4j_diag_live.py", delete=False
    ) as f:
        f.write(code)
        prog = f.name
    with tempfile.NamedTemporaryFile(
        "w", suffix="_m4j_diag_live_model.json", delete=False
    ) as f:
        f.write(model)
        model_path = f.name
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        # TCP path so the installed table actually dispatches (the
        # same-host shm arena would shadow the algorithm choice)
        "MPI4JAX_TPU_DISABLE_SHM": "1",
        "MPI4JAX_TPU_TIMEOUT_S": "60",
        "MPI4JAX_TPU_TUNE_MODEL": model_path,
        "MPI4JAX_TPU_COLL_ALGO": "allreduce=ring",
        "MPI4JAX_TPU_LIVE": "auto",
        "MPI4JAX_TPU_LIVE_WINDOW": str(window),
        "MPI4JAX_TPU_LIVE_DRIFT_PCT": "50",
        "MPI4JAX_TPU_LIVE_COOLDOWN_OPS": str(cooldown),
    }
    t0 = time.perf_counter()
    try:
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py"),
             "-n", "2", "--port", str(port), prog],
            capture_output=True, text=True, timeout=120, env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return False, f"{knobs}; live retune run hung"
    finally:
        os.unlink(prog)
        os.unlink(model_path)
    dt = time.perf_counter() - t0
    lines = {
        int(m.group(1)): (int(m.group(2)), int(m.group(3)),
                          int(m.group(4)), m.group(5))
        for m in re.finditer(
            r"diag_live (\d+) epoch (\d+) ops (\d+) errors (\d+) "
            r"changes '([^']*)'", res.stdout)
    }
    ok = (
        res.returncode == 0
        and len(lines) == 2
        # agreement: BOTH ranks installed the same nonzero epoch
        and lines[0][0] == lines[1][0] >= 1
        # the re-pick: report names the old -> new winner
        and all("ring -> rd" in v[3] for v in lines.values())
        # the commit really went through the rendezvous
        and "[live] epoch 1 committed" in res.stderr
        # controller thread never swallowed an exception
        and all(v[2] == 0 for v in lines.values())
    )
    if not ok:
        tail = (res.stderr.strip() or res.stdout.strip())[-220:]
        return False, f"{knobs}; live retune failed: {tail}"
    ops = max(v[1] for v in lines.values())
    return True, (f"{knobs}; forced model drift detected, epoch "
                  f"{lines[0][0]} rendezvous re-picked "
                  f"'{lines[0][3]}' on both ranks after {ops} ops "
                  f"in {dt:.1f}s")


def check_topology(port):
    """The topology subsystem end to end on a loopback 4-rank job
    virtually partitioned into two islands (MPI4JAX_TPU_FAKE_HOSTS):
    discovery agrees on the island map, the world arena is withheld
    while each island's intra sub-comm gets one, the native layer
    reports the installed map, the decision table defaults the 16 MB
    allreduce to the hierarchical ring, and a forced hring matches the
    flat result bit-for-bit on integer-valued floats.  The report line
    names the intra-island data plane: ``intra=ici(<backend>)`` when
    the ICI leg is active on this comm (``MPI4JAX_TPU_ICI_LEG``),
    ``intra=native`` otherwise — integer payloads keep the bit-parity
    assertions valid either way (every association sums them exactly)."""
    import tempfile

    from ..utils import config

    if config.topo_mode() == "off":
        return True, "disabled by MPI4JAX_TPU_TOPO=off (flat transport)"
    code = (
        "import sys, types, os; sys.path.insert(0, %r)\n"
        # parent-package shim: the bridge-level ranks must work even
        # where the package's jax gate blocks the full import
        "pkg = types.ModuleType('mpi4jax_tpu')\n"
        "pkg.__path__ = [os.path.join(%r, 'mpi4jax_tpu')]\n"
        "sys.modules['mpi4jax_tpu'] = pkg\n"
        "import numpy as np\n"
        "from mpi4jax_tpu import topo, tune\n"
        "from mpi4jax_tpu.runtime import bridge, transport\n"
        "c = transport.get_world_comm()\n"
        "t = c.topology()\n"
        "assert t is not None and t.multi, t\n"
        "assert t.islands == [[0, 1], [2, 3]], t.islands\n"
        "act, _, _ = bridge.shm_info(c.handle)\n"
        "assert not act, 'world arena must be withheld under FAKE_HOSTS'\n"
        "info = bridge.topo_info(c.handle)\n"
        "assert info == ([0, 0, 1, 1], 2), info\n"
        "pick = c.coll_algo('allreduce', 16 << 20)\n"
        "assert pick == 'hring', pick  # the locality-aware default\n"
        "x = np.arange(70000, dtype=np.float32) + c.rank()\n"
        # the flat reference must be FORCED: the multi-island default
        # table already resolves this payload to hring
        "ref = bridge.allreduce(c.handle, x, 0,\n"
        "                       algo=tune.ALGO_CODES['ring'])\n"
        "out = bridge.allreduce(c.handle, x, 0,\n"
        "                       algo=tune.ALGO_CODES['hring'])\n"
        "assert np.array_equal(out, ref), 'hring diverged from flat ring'\n"
        "sim = topo.simulate_hring_sum(\n"
        "    [np.arange(70000, dtype=np.float32) + r for r in range(4)],\n"
        "    t.islands)\n"
        "assert np.array_equal(out, sim), 'hring diverged from simulator'\n"
        "st = topo.ici_leg_status(c.handle)\n"
        "intra = ('ici(' + st['backend'] + ')') if st['active'] \\\n"
        "    else 'native'\n"
        "if c.rank() == 0:\n"
        "    print('topology-ok', t.render(), 'fp=' + t.fingerprint(),\n"
        "          'algo16mb=' + pick, 'intra=' + intra, flush=True)\n"
        % (REPO, REPO)
    )
    with tempfile.NamedTemporaryFile(
        "w", suffix="_m4j_diag_topo.py", delete=False
    ) as f:
        f.write(code)
        prog = f.name
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "MPI4JAX_TPU_TIMEOUT_S": os.environ.get(
            "MPI4JAX_TPU_TIMEOUT_S", "60"),
    }
    env.pop("MPI4JAX_TPU_COLL_ALGO", None)  # the check asserts defaults
    # ...including the default TABLE: a user's topology-keyed cache
    # must not steer the pick this check pins
    env["MPI4JAX_TPU_TUNE_CACHE"] = os.path.join(
        tempfile.gettempdir(), "m4j_diag_no_cache_sentinel.json")
    try:
        res = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "mpi4jax_tpu", "runtime", "launch.py"),
             "-n", "4", "--port", str(port),
             "--fake-hosts", "r0,r1|r2,r3", prog],
            capture_output=True, text=True, timeout=150, env=env, cwd=REPO,
        )
    except subprocess.TimeoutExpired:
        return False, "timed out (deadlock or port conflict?)"
    finally:
        os.unlink(prog)
    if res.returncode != 0 or "topology-ok" not in res.stdout:
        return False, (res.stderr.strip() or res.stdout.strip())[-220:]
    for line in res.stdout.splitlines():
        if line.startswith("topology-ok"):
            return True, line[len("topology-ok "):]
    return False, "no topology report line"


def check_static_verify():
    """The static communication verifier reaches correct verdicts: a
    known-bad snippet (tag mismatch) is flagged with the right finding
    kind and a known-good snippet verifies clean — all without spawning
    a process or opening a socket."""
    import tempfile

    bad = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import jax.numpy as jnp\n"
        "import mpi4jax_tpu as m4j\n"
        "comm = m4j.get_default_comm()\n"
        "x = jnp.arange(3.0)\n"
        "if comm.rank() == 0:\n"
        "    m4j.send(x, dest=1, tag=5, comm=comm)\n"
        "else:\n"
        "    m4j.recv(x, source=0, tag=7, comm=comm)\n"
    )
    good = (
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import jax.numpy as jnp\n"
        "import mpi4jax_tpu as m4j\n"
        "comm = m4j.get_default_comm()\n"
        "out = m4j.allreduce(jnp.arange(4.0), op=m4j.SUM, comm=comm)\n"
        "assert float(out[1]) == 2.0, out\n"
    )
    t0 = time.perf_counter()
    verdicts = []
    for name, src, want_rc in (("bad", bad, 3), ("good", good, 0)):
        with tempfile.NamedTemporaryFile(
            "w", suffix=f"_m4j_diag_{name}.py", delete=False
        ) as f:
            f.write(src)
            prog = f.name
        try:
            env = dict(os.environ)
            env.setdefault("PYTHONPATH", REPO)
            env.setdefault("JAX_PLATFORMS", "cpu")
            res = subprocess.run(
                [sys.executable, "-m", "mpi4jax_tpu.analyze", prog,
                 "-n", "2", "--json"],
                capture_output=True, text=True, timeout=150, env=env,
                cwd=REPO,
            )
        except subprocess.TimeoutExpired:
            return False, f"analyzer hung on the {name} snippet"
        finally:
            os.unlink(prog)
        if res.returncode != want_rc:
            return False, (
                f"{name} snippet: expected exit {want_rc}, got "
                f"{res.returncode}: {(res.stderr or res.stdout)[-150:]}"
            )
        if name == "bad":
            data = json.loads(res.stdout)
            kinds = {f["kind"] for f in data["findings"]}
            if "tag_mismatch" not in kinds:
                return False, f"bad snippet flagged as {sorted(kinds)}"
            verdicts.append("tag_mismatch flagged")
        else:
            verdicts.append("clean verified")
    dt = time.perf_counter() - t0
    return True, (f"{' + '.join(verdicts)} in {dt:.1f}s, "
                  "no process spawned, no live comm")


def check_schedule_plan(port):
    """The schedule compiler end to end: a pipeline schedule compiles
    into a plan with hoisted receives and deferred sends, the
    equivalence prover accepts it (and rejects a reordering-unsafe
    one), and a size-1 native comm executes a verified plan through the
    runner bit-identically to the direct path — ticketed posting on the
    progress engine, no processes, no sockets."""
    import ctypes

    import numpy as np

    from ..analysis import _events, _plan
    from . import bridge, planrt

    # -- compile + prove, pure analysis (no native) --------------------
    big = (64 * 1024,)

    def ev(rank, i, kind, **kw):
        return _events.CommEvent(rank, i, kind, dtype="float32",
                                 shape=big, **kw)

    pipeline = {0: [ev(0, 0, "send", dest=1, tag=0),
                    ev(0, 1, "recv", source=1, tag=0)],
                1: [ev(1, 0, "send", dest=0, tag=0),
                    ev(1, 1, "recv", source=0, tag=0)]}
    comms = {(0,): (0, 1)}
    plan = _plan.compile_schedules(pipeline, comms)
    if not (plan.proved and plan.rewritten):
        return False, f"pipeline plan not proved+rewritten: {plan.reasons}"
    if not any(op.hoisted for rp in plan.ranks.values() for op in rp.ops):
        return False, "pipeline plan hoisted no recv"
    # the deadlock-by-construction shape must be left unrewritten
    from ..analysis import _match

    unsafe = {0: [ev(0, 0, "send", dest=1, tag=0),
                  ev(0, 1, "recv", source=1, tag=0)],
              1: [ev(1, 0, "recv", source=0, tag=0),
                  ev(1, 1, "send", dest=0, tag=0)]}
    findings = _match.match_schedules(unsafe, comms)
    plan2 = _plan.compile_schedules(unsafe, comms, findings=findings)
    if plan2.rewritten or not plan2.proved:
        return False, "order-critical schedule was not left unrewritten"

    # -- execute a verified plan on a size-1 loopback comm --------------
    if not bridge.post_available():
        return False, ("native library predates ticketed posting "
                       "(no tpucomm_post); rebuild native/")
    n_msgs, shape = 3, (512,)
    events = {0: []}
    for k in range(n_msgs):
        events[0].append(_events.CommEvent(0, 2 * k, "send", dest=0,
                                           tag=k, dtype="float32",
                                           shape=shape))
        events[0].append(_events.CommEvent(0, 2 * k + 1, "recv", source=0,
                                           tag=k, dtype="float32",
                                           shape=shape))
    loop_plan = _plan.compile_schedules(events, {(0,): (0,)},
                                        detach_threshold=0)
    if not loop_plan.proved:
        return False, f"loopback plan not proved: {loop_plan.reasons}"
    h = bridge.get_lib().tpucomm_init(0, 1, int(port), b"")
    if h == 0:
        return False, "size-1 comm init failed"
    try:
        class _C:  # planrt.get keys on .handle
            handle = h

        if not planrt.install(h, loop_plan, 0):
            return False, "planrt.install refused a proved plan"
        rt = planrt.get(_C())
        for k in range(n_msgs):
            x = np.arange(shape[0], dtype=np.float32) + k
            if not rt.run_send(x, 0, k):
                return False, f"runner did not handle send {k}"
            got = rt.run_recv(shape, np.float32, 0, k)
            if got is None or not np.array_equal(got, x):
                return False, f"plan-executed loopback payload {k} wrong"
        rt.flush()
        stats = dict(rt.stats)
        if stats["mismatches"]:
            return False, f"runner reported mismatches: {stats}"
        from ..utils import config as _config

        mode = _config.plan_spec() or "off"
        return True, (f"pipeline plan proved+rewritten "
                      f"({plan.proof.get('interleavings')} interleavings), "
                      "unsafe schedule left unrewritten, plan-executed "
                      f"loopback bit-identical ({stats['deferred_sends']} "
                      f"deferred send(s), {stats['hoisted_recvs']} hoisted "
                      f"recv(s); MPI4JAX_TPU_PLAN={mode})")
    finally:
        planrt.detach(h)
        bridge.get_lib().tpucomm_finalize(ctypes.c_int64(h))


def check_device_claim():
    """A fresh process can claim the accelerator."""
    rc, out, _ = _run_snippet(
        "import jax; d = jax.devices(); print('claim-ok', d[0].platform)",
        timeout=150,
    )
    if rc is None:
        return False, ("claim HUNG (wedged by a dead claimer? wait "
                       "~15-40 min; see docs/developers.md)")
    # require an explicit non-cpu platform: when the accelerator plugin
    # fails fast, jax silently falls back to cpu and a bare "claim-ok"
    # would report the wedged device healthy (ADVICE r3 #2)
    platform = ""
    for line in out.splitlines():
        parts = line.split()
        if parts[:1] == ["claim-ok"] and len(parts) == 2:
            platform = parts[1]
    ok = rc == 0 and bool(platform) and platform != "cpu"
    detail = out.strip().splitlines()[-1] if out.strip() else "no output"
    if rc == 0 and platform == "cpu":
        detail = "claim fell back to cpu (accelerator plugin failed?)"
    return ok, detail


def check_device_compile():
    """The backend can compile + run a trivial program."""
    rc, out, err = _run_snippet(
        "import jax, jax.numpy as jnp;"
        "print('compile-ok', float(jnp.arange(8.0).sum()))",
        timeout=240,
    )
    if rc is None:
        return False, ("compile HUNG — the remote compile helper is "
                       "likely down (axon tunnel); claims may still work")
    ok = rc == 0 and "compile-ok" in out
    return ok, out.strip().splitlines()[-1] if ok else (err or out)[-200:]


def check_host_callbacks():
    """Host callbacks (the in-jit world-op path) are implemented."""
    rc, out, err = _run_snippet(
        "import jax, jax.numpy as jnp, numpy as np;"
        "f = lambda v: jax.pure_callback("
        "lambda a: np.asarray(a) * 2,"
        "jax.ShapeDtypeStruct((2,), np.float32), v);"
        "print('cb-ok', jax.jit(f)(jnp.ones(2, jnp.float32))[0])",
        timeout=240,
    )
    if rc is None:
        return False, "callback probe hung"
    if rc == 0 and "cb-ok" in out:
        return True, "pure_callback under jit"
    blob = (err or out)
    if "does not support host send/recv" in blob:
        return False, ("backend has NO host callbacks — world-tier ops "
                       "run staged-eager only (sharp-bits.md)")
    return False, blob[-200:]


def main(argv=None):
    ap = argparse.ArgumentParser(prog="python -m mpi4jax_tpu.runtime.diag")
    ap.add_argument("--device", action="store_true",
                    help="also probe the accelerator (claim/compile/"
                         "callbacks); each probe is a subprocess with a "
                         "timeout")
    ap.add_argument("--json", action="store_true")
    ap.add_argument("--port", type=int, default=45910)
    args = ap.parse_args(argv)

    checks = [
        ("native_build", check_native_build),
        ("ffi_fast_path", check_ffi),
        ("coll_algo_engine", check_coll_algo_engine),
        ("observability", lambda: check_observability(args.port + 13)),
        ("static_verify", check_static_verify),
        ("schedule_plan", lambda: check_schedule_plan(args.port + 19)),
        ("topology", lambda: check_topology(args.port + 37)),
        ("transport_loopback", lambda: check_transport_loopback(args.port)),
        ("failure_detection",
         lambda: check_failure_detection(args.port + 7)),
        ("self_healing", lambda: check_self_healing(args.port + 53)),
        ("elasticity", lambda: check_elasticity(args.port + 29)),
        ("serving", lambda: check_serving(args.port + 43)),
        ("live_retune", lambda: check_live_retune(args.port + 61)),
    ]
    if args.device:
        checks += [
            ("device_claim", check_device_claim),
            ("device_compile", check_device_compile),
            ("host_callbacks", check_host_callbacks),
        ]

    results = []
    failed = 0
    for name, fn in checks:
        t0 = time.perf_counter()
        try:
            ok, detail = fn()
        except Exception as err:
            ok, detail = False, f"{type(err).__name__}: {err}"[:200]
        dt = time.perf_counter() - t0
        failed += 0 if ok else 1
        results.append({"check": name, "ok": bool(ok),
                        "detail": str(detail), "seconds": round(dt, 1)})
        if not args.json:
            mark = "PASS" if ok else "FAIL"
            print(f"{mark}  {name:<20} {detail}  ({dt:.1f}s)", flush=True)
    if args.json:
        print(json.dumps({"results": results, "failed": failed}))
    return failed


if __name__ == "__main__":
    sys.exit(main())
