"""ctypes bridge to the native tpucomm transport (L3/L4 of the stack).

The reference's registration layer imports Cython extensions and registers
XLA custom-call targets (/root/reference/mpi4jax/_src/xla_bridge/__init__.py).
Here the native library is loaded with ctypes and invoked from *ordered host
callbacks* — on TPU that callback IS the HBM→host staging path (the
structural twin of the reference GPU bridge's
cudaMemcpy-to-host → MPI → copy-back sequence,
mpi_xla_bridge_gpu.pyx:233-251), with XLA managing the device↔host
transfers.

Fail-fast: a nonzero return from any native call prints
``tpucomm_<Op> returned error code N`` and hard-exits the process (the
analog of the reference's abort_on_error → MPI_Abort,
mpi_xla_bridge.pyx:67-91); peers then fail on their sockets and exit too.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sys
from typing import Optional

import numpy as np

from ..utils import config, dtypes as _dtypes

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
_SO_PATH = os.path.join(os.path.dirname(__file__), "_native", "libtpucomm.so")
_SRC = os.path.join(_REPO_ROOT, "native", "tpucomm.cc")
_FFI_SRC = os.path.join(_REPO_ROOT, "native", "tpucomm_ffi.cc")

_lib: Optional[ctypes.CDLL] = None


def _build() -> None:
    # Build to a temp path and atomically rename: concurrent launcher ranks
    # may rebuild simultaneously, and a sibling rank must never CDLL-load a
    # partially written .so.
    os.makedirs(os.path.dirname(_SO_PATH), exist_ok=True)
    tmp = f"{_SO_PATH}.tmp.{os.getpid()}"
    base = [
        os.environ.get("CXX", "g++"),
        "-O2", "-std=c++17", "-fPIC", "-Wall", "-pthread", "-shared",
        "-o", tmp,
    ]
    # glibc < 2.34 keeps shm_open in librt (an empty stub after): without
    # it the link succeeds but dlopen fails with an undefined symbol
    tail = ["-lrt"]
    # preferred: transport + XLA FFI handlers (needs jaxlib's bundled
    # headers); fall back to transport-only — the op layer then routes
    # through host callbacks instead of native custom calls
    try:
        if os.path.exists(_FFI_SRC):
            try:
                import jax.ffi as _jffi

                native_dir = os.path.dirname(_SRC)
                subprocess.run(
                    base
                    + [f"-I{native_dir}", f"-I{_jffi.include_dir()}",
                       _SRC, _FFI_SRC] + tail,
                    check=True, capture_output=True, text=True,
                )
                os.replace(tmp, _SO_PATH)
                return
            except subprocess.CalledProcessError as e:
                import warnings

                warnings.warn(
                    "building the native FFI fast path failed; falling back "
                    f"to a transport-only build:\n{e.stderr}"
                )
            except ImportError:
                pass
        subprocess.run(base + [_SRC] + tail, check=True)
        os.replace(tmp, _SO_PATH)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)


def _stale() -> bool:
    if not os.path.exists(_SO_PATH):
        return True
    so_mtime = os.path.getmtime(_SO_PATH)
    return any(
        os.path.exists(src) and os.path.getmtime(src) > so_mtime
        for src in (_SRC, _FFI_SRC)
    )


def get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is not None:
        return _lib
    so_path = config.native_lib_override()
    if so_path is not None:
        # explicit library (sanitizer builds, cross-build tests): load it
        # verbatim — no staleness heuristics, no rebuild
        if not os.path.exists(so_path):
            raise RuntimeError(
                f"MPI4JAX_TPU_NATIVE_LIB={so_path} does not exist"
            )
        return _finish_lib_setup(ctypes.CDLL(so_path))
    if _stale():
        if not os.path.exists(_SRC) and not os.path.exists(_SO_PATH):
            raise RuntimeError(
                f"native transport missing: no {_SO_PATH} and no source at "
                f"{_SRC} to build it from"
            )
        try:
            _build()
        except Exception as e:
            # git checkouts don't preserve mtimes, so staleness is a
            # heuristic — a shipped .so must keep working on hosts without
            # a C++ toolchain
            if not os.path.exists(_SO_PATH):
                raise
            import warnings

            warnings.warn(
                f"rebuilding stale native transport failed ({e}); using the "
                f"existing {_SO_PATH}"
            )
    return _finish_lib_setup(ctypes.CDLL(_SO_PATH))


def _finish_lib_setup(lib: ctypes.CDLL) -> ctypes.CDLL:
    global _lib, _exec_fn
    lib.tpucomm_init.restype = ctypes.c_int64
    lib.tpucomm_init.argtypes = [
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
    ]
    lib.tpucomm_set_logging.argtypes = [ctypes.c_int]
    # batched dispatch entry (async progress engine): one cached
    # descriptor struct per (comm, op) and ONE ctypes call per op —
    # no per-call marshalling of 6-8 scalar arguments (measured ~12 us
    # of Python overhead per 1 KB allreduce on the classic path, ~3 us
    # on this one).  Guarded like split/dup: a stale prebuilt .so keeps
    # serving through the classic per-op entries.
    if hasattr(lib, "tpucomm_execute"):
        lib.tpucomm_execute.restype = ctypes.c_int
        lib.tpucomm_execute.argtypes = [ctypes.c_int64, ctypes.c_void_p]
        _exec_fn = lib.tpucomm_execute
    # ticketed non-blocking posting (schedule-plan execution); guarded
    # like split/dup: a stale prebuilt .so simply reports plans
    # unavailable (post_available) instead of failing at load
    if hasattr(lib, "tpucomm_post"):
        lib.tpucomm_post.restype = ctypes.c_int64
        lib.tpucomm_post.argtypes = [ctypes.c_int64, ctypes.c_void_p]
        lib.tpucomm_wait_ticket.restype = ctypes.c_int
        lib.tpucomm_wait_ticket.argtypes = [ctypes.c_int64, ctypes.c_int64]
    # elastic recovery (guarded like split/dup: a stale prebuilt .so
    # reports recovery unavailable instead of failing at load)
    if hasattr(lib, "tpucomm_shrink"):
        lib.tpucomm_shrink.restype = ctypes.c_int64
        lib.tpucomm_shrink.argtypes = [
            ctypes.c_int64, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_char_p,
        ]
    # topology subsystem (guarded like split/dup: a stale prebuilt .so
    # keeps the flat transport; discovery then only feeds the Python
    # probes and the topology-keyed tune cache)
    if hasattr(lib, "tpucomm_set_topology"):
        lib.tpucomm_set_topology.restype = ctypes.c_int
        lib.tpucomm_set_topology.argtypes = [
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
            ctypes.c_int64, ctypes.c_int64,
        ]
        lib.tpucomm_topo_info.restype = ctypes.c_int
        lib.tpucomm_topo_info.argtypes = [
            ctypes.c_int64, ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32),
        ]
    # guarded: a stale prebuilt .so without split/dup must still serve
    # the other ops (split then fails at call time, not load time)
    if hasattr(lib, "tpucomm_split"):
        lib.tpucomm_split.restype = ctypes.c_int64
        lib.tpucomm_split.argtypes = [
            ctypes.c_int64, ctypes.c_int, ctypes.c_int,
        ]
    if hasattr(lib, "tpucomm_dup"):
        lib.tpucomm_dup.restype = ctypes.c_int64
        lib.tpucomm_dup.argtypes = [ctypes.c_int64]
    # collective algorithm engine (guarded like split/dup: a stale
    # prebuilt .so keeps serving the fixed schedules)
    if hasattr(lib, "tpucomm_set_coll_table"):
        lib.tpucomm_set_coll_table.restype = None
        lib.tpucomm_set_coll_table.argtypes = [
            ctypes.c_int, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int,
        ]
    if hasattr(lib, "tpucomm_coll_algo_for"):
        lib.tpucomm_coll_algo_for.restype = ctypes.c_int
        lib.tpucomm_coll_algo_for.argtypes = [
            ctypes.c_int64, ctypes.c_int, ctypes.c_int64,
        ]
    if config.debug_enabled():
        lib.tpucomm_set_logging(1)
    _lib = lib
    return lib


def set_native_logging(enabled: bool) -> None:
    get_lib().tpucomm_set_logging(1 if enabled else 0)


# ---------------- XLA FFI fast path ----------------
#
# Typed FFI handlers in native/tpucomm_ffi.cc, registered as cpu custom-call
# targets (≙ the reference's register_custom_call_target loop,
# xla_bridge/__init__.py:26-31 there).  When available, world-tier
# primitives lower straight to these — no Python in the dispatch path.

_FFI_TARGETS = {
    "tpucomm_shift2": "TpucommShift2Ffi",
    "tpucomm_allreduce": "TpucommAllreduceFfi",
    "tpucomm_reduce": "TpucommReduceFfi",
    "tpucomm_scan": "TpucommScanFfi",
    "tpucomm_bcast": "TpucommBcastFfi",
    "tpucomm_allgather": "TpucommAllgatherFfi",
    "tpucomm_gather": "TpucommGatherFfi",
    "tpucomm_scatter": "TpucommScatterFfi",
    "tpucomm_alltoall": "TpucommAlltoallFfi",
    "tpucomm_barrier": "TpucommBarrierFfi",
    "tpucomm_send": "TpucommSendFfi",
    "tpucomm_recv": "TpucommRecvFfi",
    "tpucomm_sendrecv": "TpucommSendrecvFfi",
    # token-operand variants (explicit-token mode wire format)
    "tpucomm_allreduce_t": "TpucommAllreduceTokFfi",
    "tpucomm_reduce_t": "TpucommReduceTokFfi",
    "tpucomm_scan_t": "TpucommScanTokFfi",
    "tpucomm_bcast_t": "TpucommBcastTokFfi",
    "tpucomm_allgather_t": "TpucommAllgatherTokFfi",
    "tpucomm_gather_t": "TpucommGatherTokFfi",
    "tpucomm_scatter_t": "TpucommScatterTokFfi",
    "tpucomm_alltoall_t": "TpucommAlltoallTokFfi",
    "tpucomm_barrier_t": "TpucommBarrierTokFfi",
    "tpucomm_send_t": "TpucommSendTokFfi",
    "tpucomm_recv_t": "TpucommRecvTokFfi",
    "tpucomm_shift2_t": "TpucommShift2TokFfi",
    "tpucomm_sendrecv_t": "TpucommSendrecvTokFfi",
}

_ffi_status: Optional[bool] = None


def ffi_available() -> bool:
    """Register the native FFI targets once; True if the fast path is up.

    Disabled by ``MPI4JAX_TPU_DISABLE_FFI=1`` (falls back to the host
    callback path) or when the library was built without the handlers.
    """
    global _ffi_status
    if _ffi_status is not None:
        return _ffi_status
    if config.ffi_disabled():
        _ffi_status = False
        return False
    if config.plan_spec() is not None:
        # schedule-plan execution lives in the host-executor layer; the
        # native FFI custom calls would bypass the plan runner entirely,
        # so a plan-enabled process keeps the callback dispatch route
        _ffi_status = False
        return False
    if config.elastic_enabled():
        # FFI lowering bakes the comm HANDLE into the compiled program
        # (an i64 attr); after a recovery rebinds the world to a fresh
        # native comm, such a baked handle would address the dead one.
        # The callback route resolves comm.handle per call, so rebound
        # comms keep working — elastic processes stay on it.
        _ffi_status = False
        return False
    try:
        import jax.ffi as jffi

        lib = get_lib()
        for target, symbol in _FFI_TARGETS.items():
            jffi.register_ffi_target(
                target, jffi.pycapsule(getattr(lib, symbol)), platform="cpu"
            )
        _ffi_status = True
    except (AttributeError, OSError, ImportError):
        _ffi_status = False
    return _ffi_status


def set_coll_table(coded_table) -> bool:
    """Push the tune package's merged decision table into the native
    layer: ``{op_kind: [(min_bytes, algo_code), ...]}``.  Returns False
    when the loaded .so predates the engine (fixed schedules serve)."""
    lib = get_lib()
    if not hasattr(lib, "tpucomm_set_coll_table"):
        return False
    for op_kind, entries in coded_table.items():
        n = len(entries)
        mins = (ctypes.c_int64 * n)(*[int(e[0]) for e in entries])
        algos = (ctypes.c_int32 * n)(*[int(e[1]) for e in entries])
        lib.tpucomm_set_coll_table(int(op_kind), mins, algos, n)
    return True


def stage_coll_table(coded_table) -> bool:
    """Park candidate decision tables in the native staging slots
    WITHOUT touching dispatch (same coding as :func:`set_coll_table`);
    :func:`commit_coll_tables` promotes them atomically.  False when
    the loaded .so predates the live re-tuning entry points."""
    lib = get_lib()
    if not hasattr(lib, "tpucomm_stage_coll_table"):
        return False
    for op_kind, entries in coded_table.items():
        n = len(entries)
        mins = (ctypes.c_int64 * n)(*[int(e[0]) for e in entries])
        algos = (ctypes.c_int32 * n)(*[int(e[1]) for e in entries])
        lib.tpucomm_stage_coll_table(int(op_kind), mins, algos, n)
    return True


def commit_coll_tables(handle, epoch: int) -> bool:
    """Promote every staged table to live under the comm lock with the
    progress engine quiesced, stamping ``epoch`` — the swap half of the
    live re-tuning protocol (all ranks call this at the same collective
    boundary).  False when the .so predates it."""
    lib = get_lib()
    if not hasattr(lib, "tpucomm_commit_coll_tables"):
        return False
    rc = lib.tpucomm_commit_coll_tables(_i64(handle), _i64(epoch))
    if rc != 0:
        raise ValueError(f"bad comm handle {handle}")
    return True


def coll_epoch():
    """The live decision-table epoch (0 = the offline-installed table),
    or None when the loaded .so predates live re-tuning."""
    lib = get_lib()
    if not hasattr(lib, "tpucomm_coll_epoch"):
        return None
    fn = lib.tpucomm_coll_epoch
    fn.restype = ctypes.c_int64
    return int(fn())


def coll_algo_for(handle, op_kind: int, nbytes: int):
    """The TpuCollAlgo code that would serve (comm, op kind, payload) —
    including the shm code when the arena path wins.  None when the
    loaded .so predates the engine."""
    lib = get_lib()
    if not hasattr(lib, "tpucomm_coll_algo_for"):
        return None
    code = lib.tpucomm_coll_algo_for(_i64(handle), int(op_kind), _i64(nbytes))
    if code < 0:
        raise ValueError(f"bad comm handle {handle}")
    return code



def uring_status():
    """Resolved state of the native io_uring submission backend:
    ``"on"``, ``"on(no-zerocopy)"``, ``"off"`` (MPI4JAX_TPU_URING=0),
    or ``"unavailable(<reason>)"`` — or None when the loaded .so
    predates the uring generation entirely (the layout probe: such a
    build has no uring path and never writes the obs ``syscalls``
    field, so it must read as uring-unavailable, not be misparsed)."""
    lib = get_lib()
    if not hasattr(lib, "tpucomm_uring_status"):
        return None
    fn = lib.tpucomm_uring_status
    fn.restype = ctypes.c_char_p
    return (fn() or b"").decode(errors="replace")


def syscall_count():
    """Process-total transport syscalls since load (write/read/writev/
    poll/io_uring_enter; futexes excluded) — benchmarks read deltas of
    this for their syscalls-per-message column.  None on a pre-uring
    .so (no counter)."""
    lib = get_lib()
    if not hasattr(lib, "tpucomm_syscall_count"):
        return None
    fn = lib.tpucomm_syscall_count
    fn.restype = ctypes.c_int64
    return int(fn())


def quant_available() -> bool:
    """True when the loaded native library carries the quantized
    collective engine (qring/qrd wire formats + the codec exports) —
    the gate the ops layer uses before routing
    ``allreduce(compression="int8")`` to the native path."""
    return hasattr(get_lib(), "tpucomm_quant_packed_bytes")


def quant_packed_bytes(count: int) -> int:
    """On-wire bytes of ``count`` elements under the native int8+scales
    codec (``4 * ceil(count/256) + count``)."""
    lib = get_lib()
    lib.tpucomm_quant_packed_bytes.restype = ctypes.c_int64
    lib.tpucomm_quant_packed_bytes.argtypes = [ctypes.c_int64]
    return int(lib.tpucomm_quant_packed_bytes(_i64(count)))


def quant_pack(buf: np.ndarray) -> np.ndarray:
    """Pack a float array through the NATIVE wire codec (the exact
    bytes qring/qrd put on the wire); returns the packed int8 buffer.
    Raises on an ineligible dtype — mirrors the engine's gate."""
    buf = _contig(buf)
    out = np.empty(quant_packed_bytes(buf.size), np.int8)
    rc = get_lib().tpucomm_quant_pack(
        _ptr(buf), _i64(buf.size), _dtypes.wire_code(buf.dtype), _ptr(out))
    if rc != 0:
        raise TypeError(
            f"dtype {buf.dtype} has no quantized wire format (real "
            "floating dtypes only)")
    return out


def quant_unpack(packed: np.ndarray, count: int, dtype) -> np.ndarray:
    """Inverse of :func:`quant_pack` (native codec)."""
    packed = _contig(packed)
    out = np.empty(int(count), dtype)
    rc = get_lib().tpucomm_quant_unpack(
        _ptr(packed), _i64(count), _dtypes.wire_code(out.dtype), _ptr(out))
    if rc != 0:
        raise TypeError(
            f"dtype {out.dtype} has no quantized wire format (real "
            "floating dtypes only)")
    return out


def shm_info(handle: int):
    """(active, slot_bytes, ring_bytes) for a comm's same-host fast
    paths — 'active' False means the comm runs on TCP only (cross-host
    members, MPI4JAX_TPU_DISABLE_SHM, or arena creation failed soft)."""
    lib = get_lib()
    if not hasattr(lib, "tpucomm_shm_info"):
        # stale prebuilt .so from before the symbol existed (get_lib
        # keeps it when a rebuild isn't possible) — report inactive
        # rather than failing a healthy transport
        return False, 0, 0
    slot = ctypes.c_int64(0)
    ring = ctypes.c_int64(0)
    rc = lib.tpucomm_shm_info(ctypes.c_int64(handle), ctypes.byref(slot),
                              ctypes.byref(ring))
    if rc < 0:
        raise ValueError(f"bad comm handle {handle}")
    return bool(rc), slot.value, ring.value


def _abort(opname: str, rc: int):
    # include the native layer's human-readable reason, the analog of the
    # reference's ierr -> MPI_Error_string conversion before MPI_Abort
    # (mpi_xla_bridge.pyx:67-91 there)
    detail = ""
    try:
        lib = get_lib()
        lib.tpucomm_last_error.restype = ctypes.c_char_p
        text = (lib.tpucomm_last_error() or b"").decode(errors="replace")
        if text:
            detail = f": {text}"
    except Exception:
        lib = None
    print(
        f"tpucomm_{opname} returned error code {rc}{detail}",
        file=sys.stderr, flush=True,
    )
    # job-wide abort propagation: poison every peer socket (non-blocking)
    # so the group tears down within one deadline instead of waiting for
    # per-rank timeouts to cascade; peers without a pending recv still
    # observe the shutdown sockets and abort as before
    try:
        if lib is not None and hasattr(lib, "tpucomm_abort_all"):
            lib.tpucomm_abort_all()
    except Exception:
        pass
    # elastic worlds (docs/elasticity.md): surface the failure as an
    # exception the recovery layer can catch instead of killing the
    # process.  The poison/shutdown above already ran, so every peer
    # unblocks within one deadline and reaches ITS recovery point too —
    # the same propagation that used to cascade the teardown now
    # cascades the recovery.  The old world is unusable either way
    # (sockets are shut down); elastic.recover() rebuilds it.
    if config.elastic_enabled():
        from ..elastic import RankFailure

        raise RankFailure(f"tpucomm_{opname} failed{detail}", op=opname)
    os._exit(1)


def _check(opname: str, rc: int):
    if rc != 0:
        _abort(opname, rc)


def comm_init(rank: int, size: int, coord: str, hosts=None) -> int:
    lib = get_lib()
    host, _, port = coord.partition(":")
    if hosts is None:
        hosts = os.environ.get("MPI4JAX_TPU_HOSTS", "")
    handle = lib.tpucomm_init(
        rank, size, int(port or 49817), hosts.encode()
    )
    if handle == 0:
        _abort("init", 1)
    _post_init_setup(lib, handle, rank, size, install_plan=True)
    return handle


def _post_init_setup(lib, handle, rank: int, size: int, *,
                     install_plan: bool) -> None:
    """The selection/telemetry layers every fresh world needs, shared by
    :func:`comm_init` and elastic recovery's :func:`rebuild`."""
    # topology discovery FIRST (it is collective, and the tune install
    # below keys the persistent cache on the discovered fingerprint).
    # MPI4JAX_TPU_TOPO=off skips it entirely; a malformed FAKE_HOSTS
    # spec stays fail-fast (the native bootstrap already exited on it).
    topology = None
    if size > 1 and config.topo_mode() != "off":
        topology = _install_topology(lib, handle, rank, size)
    # the tune layer only sees the topology when the native layer can
    # actually RUN the hierarchical schedules: on a stale .so (no
    # tpucomm_set_topology) its set_coll_table drops the unknown hring
    # code, and a flipped default table would silently degrade large
    # allreduces to the small-payload tree — discovery then serves the
    # Python probes only, and the flat defaults/caches stay in force
    tune_topology = (topology
                     if hasattr(lib, "tpucomm_set_topology") else None)
    # collective algorithm engine: load the persistent autotune cache and
    # push the merged decision table natively — every dispatch path
    # (eager / callback / FFI) then resolves the algorithm per call.
    # Soft for infrastructure problems (a selection-layer hiccup must
    # never take down a healthy transport; the built-in heuristics
    # serve), but a malformed MPI4JAX_TPU_COLL_ALGO stays fail-fast —
    # silently ignoring the operator's force is worse than stopping
    # (same contract as the boolean knob parser).
    try:
        from .. import tune

        tune.install(size, topology=tune_topology)
    except ValueError:
        raise
    except Exception as e:  # pragma: no cover - defensive
        import warnings

        warnings.warn(f"collective algorithm table install failed: {e}")
    # observability: arm the recorder when MPI4JAX_TPU_TRACE asks for a
    # dump, or re-arm (now with the native ring + clock alignment) when
    # the program called obs.start() before any comm existed.  Arming
    # MUST AGREE ACROSS RANKS (the same contract as DISABLE_SHM /
    # COLL_ALGO): the alignment handshake below is collective, so a
    # divergent condition — TRACE exported on one host of a multi-host
    # job, obs.start() on a subset of ranks — pairs one rank's
    # handshake against another rank's first user op and aborts on the
    # transport's schedule checks.  The launcher sets TRACE uniformly.
    from .. import obs

    if config.trace_path() is not None or obs.enabled():
        _install_obs(lib, handle, rank, size)
    # live re-tuning: arm the drift controller + boundary hook when
    # MPI4JAX_TPU_LIVE=auto.  Arming MUST AGREE ACROSS RANKS (the epoch
    # rendezvous bcasts at agreed boundaries — a rank without the hook
    # would pair another rank's rendezvous against its next user op);
    # the launcher exports the knob uniformly.  Knob parse errors stay
    # fail-fast; infrastructure problems degrade soft like the tune
    # install above — a live-plane hiccup must never take down a
    # healthy transport.
    if config.live_mode() == "auto":
        try:
            from .. import live

            live.arm(lib, handle, rank, size)
        except ValueError:
            raise
        except Exception as e:  # pragma: no cover - defensive
            import warnings

            warnings.warn(f"live re-tuning arm failed: {e}")
    # schedule-plan execution: when MPI4JAX_TPU_PLAN names a verified
    # plan file (launch --plan), attach this rank's schedule to the
    # world comm.  Soft like the tune install above: a bad plan file
    # warns and the job runs the historic path.
    if install_plan and config.plan_spec() is not None:
        try:
            from . import planrt

            planrt.maybe_install_from_env(handle, rank, size)
        except Exception as e:  # pragma: no cover - defensive
            import warnings

            warnings.warn(f"schedule-plan install failed: {e}")


#: topology sub-communicator handles (intra-island, leaders) cached per
#: world handle — they borrow the world's sockets, so they must be
#: finalized BEFORE the world (comm_finalize / rebuild do)
_topo_handles: dict = {}

#: per-rank ROLES of those sub-comms, keyed by world handle: the ICI
#: data-plane leg (topo/_ici_leg.py) needs to know which handle is the
#: intra comm vs the leaders comm (plus this rank / island), which the
#: positional _topo_handles list cannot encode (non-leaders have no
#: leader handle, singleton islands no intra handle)
_topo_subcomms: dict = {}

_ici_leg_mod = None

#: live re-tuning collective-boundary hook (``mpi4jax_tpu.live`` sets it
#: while armed, None otherwise): called with the comm handle at the TOP
#: of every collective wrapper, before dispatch — the point where all
#: ranks of an SPMD program are at the same per-comm collective index,
#: so an epoch rendezvous injected here lands at the same boundary
#: everywhere.  The None default keeps MPI4JAX_TPU_LIVE=off at one
#: module-global load per collective — pre-live behavior bit-for-bit.
_live_boundary = None


def set_live_boundary(fn) -> None:
    """Install (or clear, ``None``) the live boundary hook."""
    global _live_boundary
    _live_boundary = fn


def _ici_leg_hook(handle, buf, out, dtype_code, op_code, algo) -> bool:
    """Pre-dispatch probe for the ICI data-plane leg: resolves to False
    in a couple of dict lookups on ineligible calls (flat comms and
    sub-comms never have a _topo_subcomms entry; the leg is f32 SUM
    only — wire codes from native/tpucomm.h) so the native fast paths
    keep their cost profile.  The full gate chain lives in
    ``topo._ici_leg.maybe_allreduce``."""
    global _ici_leg_mod
    if int(handle) not in _topo_subcomms:
        return False
    if dtype_code != 11 or op_code != 0:
        return False
    if _ici_leg_mod is None:
        from ..topo import _ici_leg

        _ici_leg_mod = _ici_leg
    return _ici_leg_mod.maybe_allreduce(
        handle, buf, out, dtype_code, op_code, algo)


def _install_topology(lib, handle, rank: int, size: int):
    """Run the discovery handshake, derive the sub-communicators on a
    multi-island world, and install the map natively.  COLLECTIVE:
    every rank runs it at the same position inside comm_init/rebuild.
    Returns the Topology (registered for ``WorldComm.topology()``), or
    None when discovery failed soft.

    Failure softness is asymmetric: the collectives themselves abort
    the job on transport errors (nothing to soften), but a native layer
    predating the topology exports, or a set_topology rejection, leaves
    the comm FLAT with a warning — locality awareness must never take
    down a healthy transport."""
    from .. import topo

    try:
        t = topo.discover(handle, rank, size)
    except Exception as e:
        # an elastic-mode TRANSPORT failure (peer died mid-handshake)
        # must propagate as the catchable RankFailure it is — only
        # discovery-layer problems (unparseable fingerprints, mixed
        # versions) soften to a flat transport
        if config.elastic_enabled():
            from ..elastic import RankFailure

            if isinstance(e, RankFailure):
                raise
        import warnings

        warnings.warn(f"topology discovery failed; transport stays "
                      f"flat: {e}")
        return None
    subs = []
    intra_h = leader_h = None
    if t.multi and hasattr(lib, "tpucomm_set_topology"):
        # both splits are collective over the world: EVERY rank calls
        # both, members or not (color -1 opts out of the leaders comm)
        my_island = t.island_of[rank]
        intra_h = split(handle, my_island, rank)
        am_leader = rank == t.leaders[my_island]
        leader_h = split(handle, 0 if am_leader else -1, rank)
        if len(t.islands[my_island]) == 1 and intra_h is not None:
            # a singleton island's intra comm is a size-1 shell the
            # schedules never touch; drop it rather than cache it
            comm_finalize(intra_h)
            intra_h = None
        subs = [h for h in (intra_h, leader_h) if h is not None]
    if hasattr(lib, "tpucomm_set_topology"):
        arr = (ctypes.c_int32 * size)(*t.island_of)
        rc = lib.tpucomm_set_topology(
            _i64(handle), arr, size, _i64(intra_h or 0),
            _i64(leader_h or 0))
        if rc != 0:
            import warnings

            warnings.warn(
                "native topology install was rejected; hierarchical "
                "schedules stay degraded to their flat twins")
    if subs:
        _topo_handles[int(handle)] = subs
        _topo_subcomms[int(handle)] = {
            "topology": t,
            "rank": rank,
            "island": t.island_of[rank],
            "intra": intra_h,
            "leader": leader_h,
        }
    topo._register(handle, t)
    return t


def _teardown_topology(handle) -> None:
    """Finalize the cached topology sub-comms of a world handle (they
    borrow its sockets — native finalize order requires children
    first) and forget its registry entries."""
    _topo_subcomms.pop(int(handle), None)
    for sub in _topo_handles.pop(int(handle), []):
        try:
            get_lib().tpucomm_finalize(_i64(sub))
        except Exception:  # pragma: no cover - teardown path
            pass
    try:
        from .. import topo

        topo._forget(handle)
    except Exception:  # pragma: no cover - teardown path
        pass


def topo_info(handle):
    """The NATIVE layer's installed island map for a comm:
    ``(island_of, n_islands)``, or None when the comm is flat or the
    loaded .so predates the topology subsystem."""
    lib = get_lib()
    if not hasattr(lib, "tpucomm_topo_info"):
        return None
    size = comm_size(handle)
    arr = (ctypes.c_int32 * size)()
    n = ctypes.c_int32(0)
    rc = lib.tpucomm_topo_info(_i64(handle), arr, ctypes.byref(n))
    if rc == -1:
        raise ValueError(f"bad comm handle {handle}")
    if rc != 0:
        return None
    return list(arr), int(n.value)


def shrink_available() -> bool:
    """True when the loaded .so carries the elastic recovery bootstrap."""
    return hasattr(get_lib(), "tpucomm_shrink")


def rebuild(old_handle, new_rank: int, new_size: int, base_port: int,
            hosts: str = "") -> int:
    """Elastic recovery's native step (``mpi4jax_tpu.elastic`` is the
    caller): finalize the dead world (``old_handle``; 0/None when none
    was ever created) and bootstrap a fresh one over the survivors at
    the re-derived ``base_port``, then rerun the per-world setup
    (decision table for the new size, obs re-arm with a new clock
    handshake).  Schedule plans are ELASTIC-SAFE: a plan is proved for
    one (program, np) shape, so the dead world's runner is dropped and
    the plan is re-derived AND re-proved for the new size inside the
    recovery (``planrt.reinstall_after_rebuild`` — from the
    ``MPI4JAX_TPU_PLAN`` bundle or a registered plan source), and only
    a freshly-proved, signature-checked plan executes on the recovered
    world; anything less degrades loudly to the always-correct
    token-order path (docs/elasticity.md)."""
    lib = get_lib()
    if not hasattr(lib, "tpucomm_shrink"):
        raise RuntimeError(
            "elastic recovery needs a native library with the "
            "tpucomm_shrink bootstrap; rebuild native/")
    # the dead world's topology sub-comms borrow its sockets: finalize
    # them BEFORE the native shrink finalizes the world (the documented
    # sub-comm teardown order), and drop the stale Topology — the
    # rebuilt world re-discovers below, so a shrink that emptied an
    # island cleanly re-derives the (possibly now flat) map
    if old_handle:
        _teardown_topology(old_handle)
        _live_disarm()
    handle = lib.tpucomm_shrink(
        _i64(old_handle or 0), int(new_rank), int(new_size),
        int(base_port), (hosts or "").encode())
    if handle == 0:
        _abort("shrink", 1)
    _post_init_setup(lib, handle, new_rank, new_size, install_plan=False)
    # the plan layer last: the rebuilt transport/selection/obs stack is
    # live, so the re-proof can install onto a working world.  Soft
    # like the comm_init install — a plan problem must never take a
    # recovered job down.
    try:
        from . import planrt

        planrt.reinstall_after_rebuild(old_handle, handle, new_rank,
                                       new_size)
    except Exception as e:  # pragma: no cover - defensive
        import warnings

        warnings.warn(f"schedule-plan reinstall failed after recovery: "
                      f"{e}")
    return handle


def _live_disarm(handle=None) -> None:
    """Stop the live controller + clear the boundary hook, if armed
    (a dying world's hook must not rendezvous on a dead handle).
    ``handle`` restricts the disarm to that comm's controller — closing
    an unrelated sub-comm leaves the world's controller running."""
    if _live_boundary is None:
        return
    try:
        from .. import live

        live.disarm(handle=handle)
    except Exception:  # pragma: no cover - defensive teardown
        set_live_boundary(None)


def comm_finalize(handle) -> None:
    """Close one native communicator (drains its engine first; cached
    topology sub-comms go first — they borrow its sockets)."""
    _live_disarm(handle)
    _teardown_topology(handle)
    get_lib().tpucomm_finalize(_i64(handle))


_obs_dump_registered = False


def _install_obs(lib, handle, rank: int, size: int) -> None:
    """Run the clock-alignment handshake, arm the recorder, and
    schedule the per-rank dump at interpreter exit.

    The handshake is COLLECTIVE, which is why arming must agree across
    ranks (see the call site): every armed rank runs it here at the
    same program position — a barrier, then each rank samples its unix
    clock inside the same barrier-exit window and allgathers the
    samples; the median minus the local sample is this rank's offset
    onto the job-global timeline.  It runs BEFORE recording starts, so
    its own collectives never pollute the recording.

    Re-arming resets the recorder: spans recorded before the comm
    existed are dropped in favor of a recording whose every event is on
    the aligned timeline.
    """
    global _obs_dump_registered
    from .. import obs

    offset_s = 0.0
    if size > 1:
        import time

        barrier(handle)
        t_local = time.time()
        all_t = np.sort(allgather(handle, np.array([t_local], np.float64),
                                  size).ravel())
        offset_s = float(all_t[size // 2]) - t_local
    obs.start(lib=lib, rank=rank, size=size, clock_offset_s=offset_s)
    if not _obs_dump_registered:
        _obs_dump_registered = True
        import atexit

        atexit.register(_dump_obs_at_exit)


def _dump_obs_at_exit() -> None:
    base = config.trace_path()
    if base is None:
        return
    try:
        # drain pending async dispatch first: a span recorded for an op
        # whose effects have not executed yet would be a lie
        import jax

        jax.effects_barrier()
    except Exception:
        pass
    try:
        from .. import obs

        path = obs.dump(base)
        print(f"[obs] recording written to {path}", file=sys.stderr,
              flush=True)
    except Exception as e:  # pragma: no cover - defensive teardown path
        print(f"[obs] recording dump failed: {e}", file=sys.stderr,
              flush=True)


def _contig(a) -> np.ndarray:
    # NB: np.ascontiguousarray promotes 0-d arrays to 1-d; preserve shape
    a = np.asarray(a)
    return a if a.flags.c_contiguous else a.copy(order="C")


# ---------------- batched dispatch (async progress engine) ----------------
#
# Mirror of ``struct TpuOpExec`` in native/tpucomm.h (field-for-field).
# The hot wrappers below pack ONE cached descriptor per (comm, op kind)
# and make a single pre-argtyped native call instead of marshalling each
# scalar argument through ctypes on every op — the host-dispatch share
# the BENCH_r05 72 us in-jit vs 48 us transport gap is made of.

class _OpExec(ctypes.Structure):
    _fields_ = [
        ("kind", ctypes.c_int32),
        ("algo", ctypes.c_int32),
        ("sbuf", ctypes.c_void_p),
        ("rbuf", ctypes.c_void_p),
        ("snbytes", ctypes.c_int64),
        ("rnbytes", ctypes.c_int64),
        ("count", ctypes.c_int64),
        ("dtype", ctypes.c_int32),
        ("rop", ctypes.c_int32),
        ("peer", ctypes.c_int32),
        ("peer2", ctypes.c_int32),
        ("tag", ctypes.c_int32),
        ("tag2", ctypes.c_int32),
    ]


#: TpuObsOp codes (tpucomm.h) used as TpuOpExec.kind
_K_SEND, _K_RECV, _K_SENDRECV, _K_SHIFT2, _K_BARRIER, _K_BCAST = range(6)
_K_GATHER, _K_SCATTER, _K_ALLGATHER, _K_ALLTOALL = 6, 7, 8, 9
_K_ALLREDUCE, _K_REDUCE, _K_SCAN = 10, 11, 12

_exec_fn = None          # lib.tpucomm_execute with argtypes preset

# The descriptor / output-buffer caches are THREAD-LOCAL: a cached
# struct is mutated then passed to a GIL-releasing native call, so two
# threads sharing one entry could interleave mutate-and-call (the comm
# lock serializes the native side, not the Python-side packing).  Ops
# on one comm are normally serialized upstream by ordered effects, but
# "sharing one WorldComm between threads is safe" is a documented
# contract (docs/sharp-bits.md § Communicator hygiene) and stays true.
_tls = __import__("threading").local()

#: per-thread cache size bound: dicts are cleared (not evicted LRU —
#: simplicity over perfection; a clear costs one re-population) past
#: this many entries, so pathological shape churn cannot pin memory
_CACHE_CAP = 64


def _tls_cache(name):
    d = getattr(_tls, name, None)
    if d is None:
        d = {}
        setattr(_tls, name, d)
    return d


def _exec_desc(handle, kind, *const_fields):
    """The cached (handle_c, descriptor, byref) triple for one comm+op;
    callers mutate the descriptor's per-call fields and invoke
    ``_exec_fn(handle_c, ref)``.

    ``const_fields`` are (name, value) pairs baked into the cached
    descriptor: ctypes Structure field stores cost ~0.3 us each through
    the descriptor protocol, so per-op constants (dtype, reduce op,
    root, forced algorithm) are part of the cache key and written once
    instead of on every call."""
    cache = _tls_cache("exec")
    key = (handle, kind) + tuple(v for _, v in const_fields)
    ent = cache.get(key)
    if ent is None:
        if len(cache) >= _CACHE_CAP:
            cache.clear()
        d = _OpExec()
        d.kind = kind
        for name, value in const_fields:
            setattr(d, name, value)
        ent = (ctypes.c_int64(int(handle)), d, ctypes.byref(d))
        cache[key] = ent
    return ent


def _data_ptr(a: np.ndarray) -> int:
    # ~0.3 us cheaper per access than a.ctypes.data (which builds a
    # ctypeslib helper object every time) — measurable on the 1 KB path
    return a.__array_interface__["data"][0]


# Reusable output buffers for the ordered-callback hot path: a fresh
# multi-MB np.empty per op costs page faults that dominate large-message
# in-jit timings (glibc returns big frees to the kernel immediately) —
# the 16 MiB allreduce measured 0.859 GB/s/rank in-jit vs 0.935 at the
# transport before reuse.  Safe because callback results are COPIED
# into the XLA output buffer before the (ordered) callback returns;
# staged-eager dispatch must NOT use these (device_put may alias the
# numpy buffer) — the ops layer passes reuse=False there.  Keyed by
# (comm, op, shape, dtype) so alternating shapes each keep a buffer
# instead of thrashing one slot; bounded like the descriptor cache
# (large buffers bound at 16 entries per thread).
_OUT_CACHE_CAP = 16


def _reused_out(handle, kind, shape, dtype):
    """(buffer, data pointer) for the per-(comm, op, shape) reusable
    output — the pointer is cached with the buffer so the hot path
    never pays the per-access np.ctypes traversal."""
    cache = _tls_cache("out")
    shape = tuple(shape)
    key = (handle, kind, shape, dtype)
    ent = cache.get(key)
    if ent is None:
        if len(cache) >= _OUT_CACHE_CAP:
            cache.clear()
        out = np.empty(shape, dtype)
        ent = (out, _data_ptr(out))
        cache[key] = ent
    return ent


def _ptr(a: np.ndarray):
    return a.ctypes.data_as(ctypes.c_void_p)


def _i64(v) -> ctypes.c_int64:
    return ctypes.c_int64(int(v))


# ---------------- ticketed non-blocking posting (plan execution) ----------
#
# The schedule-plan runner (runtime/planrt.py) posts descriptors on the
# progress engine WITHOUT waiting: hoisted receives start reading the
# wire during host compute, deferred sends stream without blocking the
# callback.  The engine drains FIFO, so post order is wire order — the
# exact contract the analysis-side equivalence prover verified.  Every
# ticket must be waited exactly once (the runner owns that bookkeeping,
# including keeping the numpy buffers alive until the wait returns).


def post_available() -> bool:
    """True when the loaded .so carries the ticketed posting entry."""
    return hasattr(get_lib(), "tpucomm_post")


def _post(handle, d: "_OpExec") -> int:
    lib = get_lib()
    ticket = lib.tpucomm_post(_i64(handle), ctypes.byref(d))
    if ticket == 0:
        _abort("Post", 1)
    return ticket


def post_send(handle, buf: np.ndarray, dest: int, tag: int):
    """Non-blocking send post.  Returns ``(ticket, keepalive)`` — hold
    ``keepalive`` (the contiguous payload and its descriptor) until
    :func:`wait_ticket` returns for this ticket.

    OWNERSHIP CONTRACT: the caller must own ``buf``'s storage for the
    ticket's whole lifetime.  A host-callback operand ndarray does NOT
    qualify — it aliases an XLA-owned buffer that is only valid for the
    callback's duration, and the progress thread reads the descriptor
    later.  The plan runner (runtime/planrt.py) satisfies this with
    pooled payload copies; drive this entry directly only with buffers
    you allocated."""
    buf = _contig(buf)
    d = _OpExec()
    d.kind = _K_SEND
    d.sbuf = _data_ptr(buf)
    d.snbytes = buf.nbytes
    d.peer = dest
    d.tag = tag
    return _post(handle, d), (buf, d)


def post_recv_into(handle, out: np.ndarray, source: int, tag: int):
    """Non-blocking recv post into a caller-owned buffer (same
    ownership contract as :func:`post_send`: ``out`` must stay alive
    and unread until :func:`wait_ticket` returns for the ticket).
    Returns ``(ticket, keepalive)``."""
    d = _OpExec()
    d.kind = _K_RECV
    d.rbuf = _data_ptr(out)
    d.rnbytes = out.nbytes
    d.peer2 = source
    d.tag = tag
    return _post(handle, d), d


def post_recv(handle, shape, dtype, source: int, tag: int):
    """Non-blocking recv post into a fresh buffer.  Returns
    ``(ticket, out, keepalive)``; ``out`` is valid after
    :func:`wait_ticket` returns 0 for the ticket."""
    out = np.empty(shape, dtype)
    ticket, d = post_recv_into(handle, out, source, tag)
    return ticket, out, d


def wait_ticket(handle, ticket: int) -> None:
    """Block until a posted op completes; aborts the process on a
    nonzero op result exactly like the synchronous entry points."""
    rc = get_lib().tpucomm_wait_ticket(_i64(handle), ctypes.c_int64(ticket))
    _check("WaitTicket", rc)


def split(handle, color: int, key: int):
    """Collective sub-communicator creation; None when color < 0."""
    h = get_lib().tpucomm_split(_i64(handle), int(color), int(key))
    if h == 0:
        _abort("Split", 1)
    return None if h == -1 else h


def dup(handle):
    h = get_lib().tpucomm_dup(_i64(handle))
    if h == 0:
        _abort("Dup", 1)
    return h


def comm_rank(handle) -> int:
    return get_lib().tpucomm_rank(_i64(handle))


def comm_size(handle) -> int:
    return get_lib().tpucomm_size(_i64(handle))


# Every function below takes/returns contiguous numpy arrays.  The hot
# ops ride the batched descriptor entry (one cached struct + one native
# call) when the loaded .so carries it; ``reuse=True`` additionally
# reuses the output buffer per (comm, op) — callback-path only (results
# are copied into XLA buffers before the callback returns; staged-eager
# dispatch must keep fresh buffers, see _out_cache).

def send(handle, buf: np.ndarray, dest: int, tag: int):
    buf = _contig(buf)
    if _exec_fn is not None:
        hc, d, ref = _exec_desc(handle, _K_SEND)
        d.sbuf = _data_ptr(buf)
        d.snbytes = buf.nbytes
        d.peer = dest
        d.tag = tag
        _check("Send", _exec_fn(hc, ref))
        return
    rc = get_lib().tpucomm_send(
        _i64(handle), _ptr(buf), _i64(buf.nbytes), dest, tag
    )
    _check("Send", rc)


def recv(handle, shape, dtype, source: int, tag: int,
         reuse: bool = False) -> np.ndarray:
    if reuse:
        out, optr = _reused_out(handle, _K_RECV, shape, np.dtype(dtype))
    else:
        out = np.empty(shape, dtype)
        optr = None
    if _exec_fn is not None:
        hc, d, ref = _exec_desc(handle, _K_RECV)
        d.rbuf = optr if optr is not None else _data_ptr(out)
        d.rnbytes = out.nbytes
        d.peer2 = source
        d.tag = tag
        _check("Recv", _exec_fn(hc, ref))
        return out
    rc = get_lib().tpucomm_recv(
        _i64(handle), _ptr(out), _i64(out.nbytes), source, tag
    )
    _check("Recv", rc)
    return out


def recv_status(handle, shape, dtype, source: int, tag: int):
    """recv + (source, tag, byte count) from the transport frame header.

    zeros (not empty): a message shorter than the buffer fills only its
    prefix, and the tail must be deterministic, not heap garbage.
    """
    out = np.zeros(shape, dtype)
    src = ctypes.c_int32()
    tg = ctypes.c_int32()
    cnt = ctypes.c_int64()
    rc = get_lib().tpucomm_recv_status(
        _i64(handle), _ptr(out), _i64(out.nbytes), source, tag,
        ctypes.byref(src), ctypes.byref(tg), ctypes.byref(cnt),
    )
    _check("Recv", rc)
    return out, src.value, tg.value, cnt.value


def sendrecv_status(handle, sendbuf, recv_shape, recv_dtype, source, dest,
                    sendtag, recvtag):
    sendbuf = _contig(sendbuf)
    out = np.zeros(recv_shape, recv_dtype)  # deterministic short-message tail
    src = ctypes.c_int32()
    tg = ctypes.c_int32()
    cnt = ctypes.c_int64()
    rc = get_lib().tpucomm_sendrecv_status(
        _i64(handle), _ptr(sendbuf), _i64(sendbuf.nbytes), dest,
        _ptr(out), _i64(out.nbytes), source, sendtag, recvtag,
        ctypes.byref(src), ctypes.byref(tg), ctypes.byref(cnt),
    )
    _check("Sendrecv", rc)
    return out, src.value, tg.value, cnt.value


def sendrecv(handle, sendbuf, recv_shape, recv_dtype, source, dest, tag,
             reuse: bool = False):
    sendbuf = _contig(sendbuf)
    optr = None
    if reuse:
        out, optr = _reused_out(handle, _K_SENDRECV, recv_shape,
                                np.dtype(recv_dtype))
        if out is sendbuf:  # eager chain fed the cached out back in
            out, optr = np.empty(recv_shape, recv_dtype), None
    else:
        out = np.empty(recv_shape, recv_dtype)
    if _exec_fn is not None:
        hc, d, ref = _exec_desc(handle, _K_SENDRECV)
        d.sbuf = _data_ptr(sendbuf)
        d.snbytes = sendbuf.nbytes
        d.peer = dest
        d.rbuf = optr if optr is not None else _data_ptr(out)
        d.rnbytes = out.nbytes
        d.peer2 = source
        d.tag = tag
        d.tag2 = tag
        _check("Sendrecv", _exec_fn(hc, ref))
        return out
    rc = get_lib().tpucomm_sendrecv(
        _i64(handle), _ptr(sendbuf), _i64(sendbuf.nbytes), dest,
        _ptr(out), _i64(out.nbytes), source, tag,
    )
    _check("Sendrecv", rc)
    return out


def shift2(handle, buf, lo: int, hi: int, tag: int) -> np.ndarray:
    """Bidirectional neighbor exchange: ``buf`` is the (2, ...) stack
    [to_lo, to_hi]; returns [from_lo, from_hi] (walls = passthrough)."""
    buf = _contig(buf)
    out = np.empty_like(buf)
    if _exec_fn is not None:
        hc, d, ref = _exec_desc(handle, _K_SHIFT2)
        d.sbuf = _data_ptr(buf)
        d.rbuf = _data_ptr(out)
        d.snbytes = buf.nbytes // 2
        d.peer = int(lo)
        d.peer2 = int(hi)
        d.tag = int(tag)
        _check("Shift2", _exec_fn(hc, ref))
        return out
    rc = get_lib().tpucomm_shift2(
        _i64(handle), _ptr(buf), _ptr(out), _i64(buf.nbytes // 2),
        int(lo), int(hi), int(tag),
    )
    _check("Shift2", rc)
    return out


def barrier(handle):
    if _live_boundary is not None:
        _live_boundary(handle)
    if _exec_fn is not None:
        hc, _, ref = _exec_desc(handle, _K_BARRIER)
        _check("Barrier", _exec_fn(hc, ref))
        return
    _check("Barrier", get_lib().tpucomm_barrier(_i64(handle)))


def bcast(handle, buf, root) -> np.ndarray:
    if _live_boundary is not None:
        _live_boundary(handle)
    out = _contig(buf).copy()
    if _exec_fn is not None:
        hc, d, ref = _exec_desc(handle, _K_BCAST, ("peer", root))
        d.rbuf = _data_ptr(out)
        d.rnbytes = out.nbytes
        _check("Bcast", _exec_fn(hc, ref))
        return out
    rc = get_lib().tpucomm_bcast(_i64(handle), _ptr(out), _i64(out.nbytes), root)
    _check("Bcast", rc)
    return out


def allreduce_raw(handle, buf: np.ndarray, out: np.ndarray, dtype_code: int,
                  op_code: int, algo: Optional[int] = None):
    """Zero-marshalling allreduce over pre-shaped contiguous buffers —
    the tuner/benchmark inner loop.  ``algo`` is a TpuCollAlgo code
    forced for this call (None/0 = engine selection); forcing against a
    pre-engine .so raises — silently running the default schedule under
    a forced label would poison equivalence tests and tuning data.

    The ICI data-plane leg (``topo/_ici_leg.py``) intercepts BEFORE
    both native paths: an eligible hierarchical f32 SUM on a topology
    comm runs its intra-island phase over the Pallas ring instead of
    the native shm/TCP legs (quiet fallthrough otherwise — the knob
    parser is the loud guard)."""
    if _live_boundary is not None:
        _live_boundary(handle)
    if _ici_leg_hook(handle, buf, out, dtype_code, op_code, algo):
        return
    if _exec_fn is not None:
        hc, d, ref = _exec_desc(handle, _K_ALLREDUCE, ("dtype", dtype_code),
                                ("rop", op_code), ("algo", int(algo or 0)))
        d.sbuf = _data_ptr(buf)
        d.rbuf = _data_ptr(out)
        d.count = buf.size
        _check("Allreduce", _exec_fn(hc, ref))
        return
    lib = get_lib()
    if algo and not hasattr(lib, "tpucomm_allreduce_algo"):
        raise RuntimeError(
            "forced collective algorithms need a native library with the "
            "algorithm engine (tpucomm_allreduce_algo); rebuild native/"
        )
    if algo:
        rc = lib.tpucomm_allreduce_algo(
            _i64(handle), _ptr(buf), _ptr(out), _i64(buf.size),
            dtype_code, op_code, int(algo),
        )
    else:
        rc = lib.tpucomm_allreduce(
            _i64(handle), _ptr(buf), _ptr(out), _i64(buf.size),
            dtype_code, op_code,
        )
    _check("Allreduce", rc)


def allreduce(handle, buf, op_code: int, out: Optional[np.ndarray] = None,
              algo: Optional[int] = None, reuse: bool = False) -> np.ndarray:
    """``out`` lets hot loops reuse the result buffer: a fresh multi-MB
    allocation per call costs page faults that dominate large-message
    timings (glibc returns big frees to the kernel immediately).
    ``reuse=True`` does the same per (comm, op, shape) automatically —
    safe on the ordered-callback path only (see _reused_out)."""
    buf = _contig(buf)
    if out is None and reuse and _exec_fn is not None:
        # fused fast path for the hottest op: one (thread-local) dict
        # hit resolves the handle, the fully-populated descriptor (out
        # pointer and count baked in), AND the reusable output buffer —
        # the steady state pays one input pointer fetch, one field
        # store, and one native call
        cache = _tls_cache("ar")
        key = (handle, buf.dtype.num, buf.shape, op_code, algo or 0)
        ent = cache.get(key)
        if ent is None:
            if len(cache) >= _OUT_CACHE_CAP:
                cache.clear()
            res = np.empty_like(buf)
            d = _OpExec()
            d.kind = _K_ALLREDUCE
            d.dtype = _dtypes.wire_code(buf.dtype)
            d.rop = op_code
            d.algo = int(algo or 0)
            d.rbuf = _data_ptr(res)
            d.count = buf.size
            ent = (ctypes.c_int64(int(handle)), ctypes.byref(d), res, d)
            cache[key] = ent
        hc, ref, res = ent[0], ent[1], ent[2]
        if res is not buf:
            # the fused path returns before allreduce_raw, so it pays
            # the boundary hook itself (exactly once per collective)
            if _live_boundary is not None:
                _live_boundary(handle)
            ent[3].sbuf = _data_ptr(buf)
            _check("Allreduce", _exec_fn(hc, ref))
            return res
    if out is None and reuse:
        cached, _ = _reused_out(handle, _K_ALLREDUCE, buf.shape, buf.dtype)
        if cached is not buf:
            out = cached
    if (out is None or out.shape != buf.shape or out.dtype != buf.dtype
            or not out.flags.c_contiguous or out is buf):
        out = np.empty_like(buf)
    allreduce_raw(handle, buf, out, _dtypes.wire_code(buf.dtype), op_code,
                  algo=algo)
    return out


def reduce(handle, buf, op_code: int, root: int,
           reuse: bool = False) -> np.ndarray:
    if _live_boundary is not None:
        _live_boundary(handle)
    buf = _contig(buf)
    optr = None
    if reuse:
        out, optr = _reused_out(handle, _K_REDUCE, buf.shape, buf.dtype)
        if out is buf:
            out, optr = np.empty_like(buf), None
    else:
        out = np.empty_like(buf)
    if _exec_fn is not None:
        hc, d, ref = _exec_desc(
            handle, _K_REDUCE, ("dtype", _dtypes.wire_code(buf.dtype)),
            ("rop", op_code), ("peer", root))
        d.sbuf = _data_ptr(buf)
        d.rbuf = optr if optr is not None else _data_ptr(out)
        d.count = buf.size
        _check("Reduce", _exec_fn(hc, ref))
        return out
    rc = get_lib().tpucomm_reduce(
        _i64(handle), _ptr(buf), _ptr(out), _i64(buf.size),
        _dtypes.wire_code(buf.dtype), op_code, root,
    )
    _check("Reduce", rc)
    return out


def scan(handle, buf, op_code: int, reuse: bool = False) -> np.ndarray:
    if _live_boundary is not None:
        _live_boundary(handle)
    buf = _contig(buf)
    optr = None
    if reuse:
        out, optr = _reused_out(handle, _K_SCAN, buf.shape, buf.dtype)
        if out is buf:
            out, optr = np.empty_like(buf), None
    else:
        out = np.empty_like(buf)
    if _exec_fn is not None:
        hc, d, ref = _exec_desc(
            handle, _K_SCAN, ("dtype", _dtypes.wire_code(buf.dtype)),
            ("rop", op_code))
        d.sbuf = _data_ptr(buf)
        d.rbuf = optr if optr is not None else _data_ptr(out)
        d.count = buf.size
        _check("Scan", _exec_fn(hc, ref))
        return out
    rc = get_lib().tpucomm_scan(
        _i64(handle), _ptr(buf), _ptr(out), _i64(buf.size),
        _dtypes.wire_code(buf.dtype), op_code,
    )
    _check("Scan", rc)
    return out


def allgather_raw(handle, buf: np.ndarray, out: np.ndarray,
                  algo: Optional[int] = None):
    """Zero-marshalling allgather (tuner/benchmark inner loop); ``algo``
    as in :func:`allreduce_raw` (raises on a pre-engine .so)."""
    if _live_boundary is not None:
        _live_boundary(handle)
    if _exec_fn is not None:
        hc, d, ref = _exec_desc(handle, _K_ALLGATHER,
                                ("algo", int(algo or 0)))
        d.sbuf = _data_ptr(buf)
        d.snbytes = buf.nbytes
        d.rbuf = _data_ptr(out)
        _check("Allgather", _exec_fn(hc, ref))
        return
    lib = get_lib()
    if algo and not hasattr(lib, "tpucomm_allgather_algo"):
        raise RuntimeError(
            "forced collective algorithms need a native library with the "
            "algorithm engine (tpucomm_allgather_algo); rebuild native/"
        )
    if algo:
        rc = lib.tpucomm_allgather_algo(
            _i64(handle), _ptr(buf), _i64(buf.nbytes), _ptr(out), int(algo)
        )
    else:
        rc = lib.tpucomm_allgather(
            _i64(handle), _ptr(buf), _i64(buf.nbytes), _ptr(out)
        )
    _check("Allgather", rc)


def allgather(handle, buf, size: int, algo: Optional[int] = None,
              reuse: bool = False) -> np.ndarray:
    buf = _contig(buf)
    if reuse:
        out, optr = _reused_out(handle, _K_ALLGATHER, (size,) + buf.shape,
                                buf.dtype)
        if _exec_fn is not None:
            # returns before allgather_raw: pay the boundary hook here
            if _live_boundary is not None:
                _live_boundary(handle)
            hc, d, ref = _exec_desc(handle, _K_ALLGATHER,
                                    ("algo", int(algo or 0)))
            d.sbuf = _data_ptr(buf)
            d.snbytes = buf.nbytes
            d.rbuf = optr
            _check("Allgather", _exec_fn(hc, ref))
            return out
    else:
        out = np.empty((size,) + buf.shape, buf.dtype)
    allgather_raw(handle, buf, out, algo=algo)
    return out


def gather(handle, buf, size: int, root: int, rank: int) -> np.ndarray:
    if _live_boundary is not None:
        _live_boundary(handle)
    buf = _contig(buf)
    # non-root only sends (the native call ignores recvbuf off-root) and
    # gets its input back — the exact reference contract
    # (gather.py:213-226 there)
    out = np.empty((size,) + buf.shape, buf.dtype) if rank == root else buf
    if _exec_fn is not None:
        hc, d, ref = _exec_desc(handle, _K_GATHER)
        d.sbuf = _data_ptr(buf)
        d.snbytes = buf.nbytes
        d.rbuf = _data_ptr(out)
        d.peer = root
        _check("Gather", _exec_fn(hc, ref))
        return out
    rc = get_lib().tpucomm_gather(
        _i64(handle), _ptr(buf), _i64(buf.nbytes), _ptr(out), root
    )
    _check("Gather", rc)
    return out


def scatter(handle, buf, root: int) -> np.ndarray:
    if _live_boundary is not None:
        _live_boundary(handle)
    buf = _contig(buf)
    out = np.empty(buf.shape[1:], buf.dtype)
    if _exec_fn is not None:
        hc, d, ref = _exec_desc(handle, _K_SCATTER)
        d.sbuf = _data_ptr(buf)
        d.rbuf = _data_ptr(out)
        d.rnbytes = out.nbytes
        d.peer = root
        _check("Scatter", _exec_fn(hc, ref))
        return out
    rc = get_lib().tpucomm_scatter(
        _i64(handle), _ptr(buf), _ptr(out), _i64(out.nbytes), root
    )
    _check("Scatter", rc)
    return out


def alltoall_raw(handle, buf: np.ndarray, out: np.ndarray,
                 algo: Optional[int] = None,
                 dtype_code: Optional[int] = None):
    """Zero-marshalling alltoall (tuner/benchmark inner loop); ``algo``
    as in :func:`allreduce_raw` (raises on a pre-engine .so).

    The typed entry (per-chunk element count + dtype) is what makes the
    quantized/hierarchical schedules (qalltoall/halltoall/hqalltoall)
    resolvable — the legacy byte-chunk call always runs the exact
    exchange.  ``dtype_code`` overrides the wire code derived from
    ``buf.dtype`` (bf16 payloads carried as uint16 bit views).
    """
    if _live_boundary is not None:
        _live_boundary(handle)
    count = buf.size // buf.shape[0]
    if dtype_code is None:
        dtype_code = _dtypes.wire_code(buf.dtype)
    if _exec_fn is not None:
        hc, d, ref = _exec_desc(handle, _K_ALLTOALL,
                                ("dtype", int(dtype_code)),
                                ("algo", int(algo or 0)))
        d.sbuf = _data_ptr(buf)
        d.rbuf = _data_ptr(out)
        d.count = count
        _check("Alltoall", _exec_fn(hc, ref))
        return
    lib = get_lib()
    if not hasattr(lib, "tpucomm_alltoall_algo"):
        if algo:
            raise RuntimeError(
                "forced collective algorithms need a native library with "
                "the algorithm engine (tpucomm_alltoall_algo); rebuild "
                "native/"
            )
        rc = lib.tpucomm_alltoall(
            _i64(handle), _ptr(buf), _ptr(out),
            _i64(buf.nbytes // buf.shape[0])
        )
    else:
        rc = lib.tpucomm_alltoall_algo(
            _i64(handle), _ptr(buf), _ptr(out), _i64(count),
            int(dtype_code), int(algo or 0)
        )
    _check("Alltoall", rc)


def alltoall(handle, buf, algo: Optional[int] = None) -> np.ndarray:
    buf = _contig(buf)
    out = np.empty_like(buf)
    alltoall_raw(handle, buf, out, algo=algo)
    return out
