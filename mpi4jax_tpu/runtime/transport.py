"""World-tier (multi-process) communicator.

This module is the Python face of the native C++ transport (``native/``),
which replaces the reference's libmpi substrate
(/root/reference/mpi4jax/_src/xla_bridge/mpi_xla_bridge.pyx) — this
environment ships no MPI, and on TPU pods the equivalent role (host-side
cross-process bytes over DCN) is played by our own TCP transport.

Process model: one process per rank, launched by
``python -m mpi4jax_tpu.runtime.launch -n N prog.py`` which sets
``MPI4JAX_TPU_RANK`` / ``MPI4JAX_TPU_SIZE`` / ``MPI4JAX_TPU_COORD``.
"""

from __future__ import annotations

import os
from typing import Optional

ENV_RANK = "MPI4JAX_TPU_RANK"
ENV_SIZE = "MPI4JAX_TPU_SIZE"
ENV_COORD = "MPI4JAX_TPU_COORD"


def in_world() -> bool:
    """True when this process was launched as a rank of a world job."""
    return ENV_RANK in os.environ and ENV_SIZE in os.environ


_world: Optional["WorldComm"] = None


def get_world_comm() -> "WorldComm":
    global _world
    if _world is None:
        if not in_world():
            raise RuntimeError(
                "not running under the mpi4jax_tpu launcher; start with "
                "`python -m mpi4jax_tpu.runtime.launch -n <ranks> prog.py` "
                "or use the mesh tier (mpi4jax_tpu.spmd) in a single process"
            )
        _world = WorldComm(
            rank=int(os.environ[ENV_RANK]),
            size=int(os.environ[ENV_SIZE]),
            coord=os.environ.get(ENV_COORD, "127.0.0.1:49817"),
        )
    return _world


class WorldComm:
    """One-process-per-rank communicator backed by the native transport."""

    def __init__(self, rank: int, size: int, coord: str):
        self._rank = rank
        self._size = size
        self._coord = coord
        self._handle = None  # native comm handle, created lazily

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._size

    def __repr__(self):
        return f"WorldComm(rank={self._rank}, size={self._size})"

    def __hash__(self):
        return hash(("mpi4jax_tpu.WorldComm", self._size))

    def __eq__(self, other):
        return (
            isinstance(other, WorldComm)
            and other._size == self._size
            and other._rank == self._rank
        )

    def __enter__(self):
        from ..parallel.mesh import _push_comm

        _push_comm(self)
        return self

    def __exit__(self, *exc):
        from ..parallel.mesh import _pop_comm

        _pop_comm(self)
        return False

    @property
    def handle(self) -> int:
        """Native communicator id (connects the TCP mesh on first use)."""
        if self._handle is None:
            from . import bridge

            self._handle = bridge.comm_init(self._rank, self._size, self._coord)
        return self._handle
