"""World-tier (multi-process) communicator.

This module is the Python face of the native C++ transport (``native/``),
which replaces the reference's libmpi substrate
(/root/reference/mpi4jax/_src/xla_bridge/mpi_xla_bridge.pyx) — this
environment ships no MPI, and on TPU pods the equivalent role (host-side
cross-process bytes over DCN) is played by our own TCP transport.

Process model: one process per rank, launched by
``python -m mpi4jax_tpu.runtime.launch -n N prog.py`` which sets
``MPI4JAX_TPU_RANK`` / ``MPI4JAX_TPU_SIZE`` / ``MPI4JAX_TPU_COORD``.
"""

from __future__ import annotations

import os
from typing import Optional

ENV_RANK = "MPI4JAX_TPU_RANK"
ENV_SIZE = "MPI4JAX_TPU_SIZE"
ENV_COORD = "MPI4JAX_TPU_COORD"

# Foreign launcher adoption: a job started by mpirun / srun / a PMI-style
# launcher already carries rank/size in its environment — accept those so
# this framework is a drop-in for `mpirun -n N python prog.py` workflows
# (the reference's only launch mode, README.rst:73-77 there).  Pairs are
# checked in order; the native launcher's own variables win.
_FOREIGN_RANK_SIZE = (
    (ENV_RANK, ENV_SIZE),
    ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),  # Open MPI mpirun
    ("PMI_RANK", "PMI_SIZE"),                          # MPICH / PMI-1
    ("SLURM_PROCID", "SLURM_NTASKS"),                  # srun
)


def _detect_rank_size():
    """(rank, size) from the first launcher env pair present, else None.

    The SLURM pair alone is NOT a world signal: every *batch step*
    exports ``SLURM_PROCID=0``/``SLURM_NTASKS=N`` into plain ``python``
    invocations too, and adopting those would hang single-process
    mesh-tier programs waiting for N-1 phantom peers.  srun-launched
    tasks additionally carry ``SLURM_LAUNCH_NODE_IPADDR``, so that is
    required for the SLURM pair.

    Multi-host jobs must also give every rank the per-rank host table
    via ``MPI4JAX_TPU_HOSTS`` (the coord var only carries the base
    port); same-host jobs work with the defaults.
    """
    for rank_var, size_var in _FOREIGN_RANK_SIZE:
        if rank_var in os.environ and size_var in os.environ:
            if (rank_var == "SLURM_PROCID"
                    and "SLURM_LAUNCH_NODE_IPADDR" not in os.environ):
                continue
            return int(os.environ[rank_var]), int(os.environ[size_var])
    return None


def in_world() -> bool:
    """True when this process was launched as a rank of a world job
    (by this framework's launcher, mpirun, srun, or any PMI launcher)."""
    return _detect_rank_size() is not None


_world: Optional["WorldComm"] = None


def get_world_comm() -> "WorldComm":
    global _world
    if _world is None:
        rs = _detect_rank_size()
        if rs is None:
            raise RuntimeError(
                "not running under a world launcher; start with "
                "`python -m mpi4jax_tpu.runtime.launch -n <ranks> prog.py` "
                "(or mpirun/srun — OMPI_*/PMI_*/SLURM_* env is adopted), "
                "or use the mesh tier (mpi4jax_tpu.spmd) in a single process"
            )
        _world = WorldComm(
            rank=rs[0],
            size=rs[1],
            coord=os.environ.get(ENV_COORD, "127.0.0.1:49817"),
        )
    return _world


class WorldComm:
    """One-process-per-rank communicator backed by the native transport.

    ``split``/``dup`` create sub-communicators over the same transport,
    the analog of the reference's arbitrary-mpi4py-comm support (users
    Split()/Clone() freely, /root/reference/mpi4jax/_src/comm.py:4-11 and
    docs/sharp-bits.rst:82-143 there).
    """

    def __init__(self, rank: int, size: int, coord: str, *, handle=None,
                 lineage=(0,), parent=None):
        self._rank = rank
        self._size = size
        self._coord = coord
        self._handle = handle  # native comm handle, created lazily
        # identity of this comm in the split tree: (0,) is the world;
        # children append (call seq, color).  Deterministic across ranks,
        # so primitive-param hashes — and therefore cached jaxprs — agree
        # process-wide (the reference's stable-hash requirement,
        # utils.py:133-152 there).  Computed without touching the native
        # handle: hashing must not force a TCP connection at trace time.
        self._lineage = lineage
        self._split_seq = 0
        # keep the parent alive: children borrow its sockets
        self._parent = parent

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._size

    def split(self, color: int, key=None):
        """Collective: ranks sharing ``color`` form a new communicator,
        ordered by ``(key, parent rank)`` (``key`` defaults to the parent
        rank). ``color < 0`` opts this rank out and returns None.

        Every member of this comm must call ``split`` at the same program
        point (it is itself a collective over the parent transport).
        """
        from . import bridge

        color = int(color)
        key = self._rank if key is None else int(key)
        self._split_seq += 1  # mirrors the native collective-call counter
        seq = self._split_seq
        handle = bridge.split(self.handle, color, key)
        if handle is None:
            return None
        return WorldComm(
            bridge.comm_rank(handle),
            bridge.comm_size(handle),
            self._coord,
            handle=handle,
            lineage=self._lineage + (seq, color),
            parent=self,
        )

    def dup(self):
        """Collective: same membership, isolated message space (the
        reference's default-comm Clone() hygiene, comm.py:4-11 there)."""
        from . import bridge

        # native dup is split(color=0, key=rank) underneath — mirror its
        # collective-call counter so lineage stays in sync with comm_id
        self._split_seq += 1
        seq = self._split_seq
        handle = bridge.dup(self.handle)
        return WorldComm(
            self._rank,
            self._size,
            self._coord,
            handle=handle,
            lineage=self._lineage + (seq, 0),
            parent=self,
        )

    clone = dup
    Clone = dup
    Split = split

    def __repr__(self):
        kind = "WorldComm" if self._parent is None else "SubComm"
        return f"{kind}(rank={self._rank}, size={self._size})"

    def __hash__(self):
        return hash(("mpi4jax_tpu.WorldComm", self._size, self._lineage))

    def __eq__(self, other):
        return (
            isinstance(other, WorldComm)
            and other._size == self._size
            and other._rank == self._rank
            and other._lineage == self._lineage
        )

    def __enter__(self):
        from ..parallel.mesh import _push_comm

        _push_comm(self)
        return self

    def __exit__(self, *exc):
        from ..parallel.mesh import _pop_comm

        _pop_comm(self)
        return False

    @property
    def handle(self) -> int:
        """Native communicator id (connects the TCP mesh on first use)."""
        if self._handle is None:
            from . import bridge

            self._handle = bridge.comm_init(self._rank, self._size, self._coord)
        return self._handle
