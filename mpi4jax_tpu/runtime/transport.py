"""World-tier (multi-process) communicator.

This module is the Python face of the native C++ transport (``native/``),
which replaces the reference's libmpi substrate
(/root/reference/mpi4jax/_src/xla_bridge/mpi_xla_bridge.pyx) — this
environment ships no MPI, and on TPU pods the equivalent role (host-side
cross-process bytes over DCN) is played by our own TCP transport.

Process model: one process per rank, launched by
``python -m mpi4jax_tpu.runtime.launch -n N prog.py`` which sets
``MPI4JAX_TPU_RANK`` / ``MPI4JAX_TPU_SIZE`` / ``MPI4JAX_TPU_COORD``.

Failure contract: every blocking transport wait is bounded when
``MPI4JAX_TPU_TIMEOUT_S`` is set (progress-based — the clock resets on
any byte moved), bootstrap is bounded by
``MPI4JAX_TPU_CONNECT_TIMEOUT_S``, and an aborting rank poisons its
peers so the group tears down within one deadline (docs/sharp-bits.md
§ "Hangs, timeouts, and teardown").  The knobs are read in the native
layer; ``utils/config.py`` is the registry.
"""

from __future__ import annotations

import os
from typing import Optional

ENV_RANK = "MPI4JAX_TPU_RANK"
ENV_SIZE = "MPI4JAX_TPU_SIZE"
ENV_COORD = "MPI4JAX_TPU_COORD"

# Foreign launcher adoption: a job started by mpirun / srun / a PMI-style
# launcher already carries rank/size in its environment — accept those so
# this framework is a drop-in for `mpirun -n N python prog.py` workflows
# (the reference's only launch mode, README.rst:73-77 there).  Pairs are
# checked in order; the native launcher's own variables win.
_FOREIGN_RANK_SIZE = (
    (ENV_RANK, ENV_SIZE),
    ("OMPI_COMM_WORLD_RANK", "OMPI_COMM_WORLD_SIZE"),  # Open MPI mpirun
    ("PMI_RANK", "PMI_SIZE"),                          # MPICH / PMI-1
    ("SLURM_PROCID", "SLURM_NTASKS"),                  # srun
)


def _detect_rank_size():
    """(rank, size) from the first launcher env pair present, else None.

    The SLURM pair alone is NOT a world signal: every *batch step*
    exports ``SLURM_PROCID=0``/``SLURM_NTASKS=N`` into plain ``python``
    invocations too, and adopting those would hang single-process
    mesh-tier programs waiting for N-1 phantom peers.  srun-launched
    tasks additionally carry ``SLURM_LAUNCH_NODE_IPADDR``, so that is
    required for the SLURM pair.

    Multi-host jobs must also give every rank the per-rank host table
    via ``MPI4JAX_TPU_HOSTS`` (the coord var only carries the base
    port); same-host jobs work with the defaults.
    """
    for rank_var, size_var in _FOREIGN_RANK_SIZE:
        if rank_var in os.environ and size_var in os.environ:
            if (rank_var == "SLURM_PROCID"
                    and "SLURM_LAUNCH_NODE_IPADDR" not in os.environ):
                continue
            return int(os.environ[rank_var]), int(os.environ[size_var])
    return None


def in_world() -> bool:
    """True when this process was launched as a rank of a world job
    (by this framework's launcher, mpirun, srun, or any PMI launcher)."""
    return _detect_rank_size() is not None


_world: Optional["WorldComm"] = None


def get_world_comm() -> "WorldComm":
    global _world
    if _world is None:
        rs = _detect_rank_size()
        if rs is None:
            raise RuntimeError(
                "not running under a world launcher; start with "
                "`python -m mpi4jax_tpu.runtime.launch -n <ranks> prog.py` "
                "(or mpirun/srun — OMPI_*/PMI_*/SLURM_* env is adopted), "
                "or use the mesh tier (mpi4jax_tpu.spmd) in a single process"
            )
        _world = WorldComm(
            rank=rs[0],
            size=rs[1],
            coord=os.environ.get(ENV_COORD) or _default_coord(),
        )
    return _world


def _free_port_block(size: int) -> int:
    """A base port such that base..base+size-2 are bindable locally
    (rank r listens on base+r; remote-host collisions surface as the
    native init's fail-fast)."""
    import random
    import socket

    for _ in range(50):
        base = random.randrange(42000, 48000)
        ok = True
        for off in range(max(size - 1, 1)):
            s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            try:
                s.bind(("127.0.0.1", base + off))
            except OSError:
                ok = False
            finally:
                s.close()
            if not ok:
                break
        if ok:
            return base
    raise RuntimeError("no free port block found for from_mpi bootstrap")


def _default_coord() -> str:
    """Rendezvous default when MPI4JAX_TPU_COORD is unset (foreign
    launchers: mpirun/srun/PMI).  A fixed port would collide when two
    jobs share a host (ADVICE r4), so derive it from a job-unique token
    every rank of one job sees identically — no token means single-job
    hosts, where the fixed default is fine.  Multi-job hosts without a
    recognized token should set MPI4JAX_TPU_COORD explicitly
    (docs/installation.md)."""
    # PMIX_NAMESPACE covers Open MPI >= 5 (ORTE/ess removed; PMIx
    # publishes the job namespace instead)
    for var in ("OMPI_MCA_ess_base_jobid", "PMIX_NAMESPACE", "SLURM_JOB_ID",
                "PMI_JOBID", "PBS_JOBID", "LSB_JOBID"):
        tok = os.environ.get(var)
        if tok:
            # stable across ranks (no PYTHONHASHSEED dependence)
            import zlib

            port = 41000 + (zlib.crc32(tok.encode()) % 8000)
            return f"127.0.0.1:{port}"
    return "127.0.0.1:49817"


class WorldComm:
    """One-process-per-rank communicator backed by the native transport.

    ``split``/``dup`` create sub-communicators over the same transport,
    the analog of the reference's arbitrary-mpi4py-comm support (users
    Split()/Clone() freely, /root/reference/mpi4jax/_src/comm.py:4-11 and
    docs/sharp-bits.rst:82-143 there).
    """

    def __init__(self, rank: int, size: int, coord: str, *, handle=None,
                 lineage=(0,), parent=None, hosts=None):
        self._rank = rank
        self._size = size
        self._coord = coord
        self._hosts = hosts    # per-rank host table (else MPI4JAX_TPU_HOSTS)
        self._handle = handle  # native comm handle, created lazily
        # identity of this comm in the split tree: (0,) is the world;
        # children append (call seq, color).  Deterministic across ranks,
        # so primitive-param hashes — and therefore cached jaxprs — agree
        # process-wide (the reference's stable-hash requirement,
        # utils.py:133-152 there).  Computed without touching the native
        # handle: hashing must not force a TCP connection at trace time.
        self._lineage = lineage
        self._split_seq = 0
        # keep the parent alive: children borrow its sockets
        self._parent = parent

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._size

    def _rebind(self, rank: int, size: int, coord: str, handle) -> None:
        """Elastic recovery (``mpi4jax_tpu.elastic``) rebinds THIS
        object onto the rebuilt native communicator, so every held
        reference — jitted closures, the default-comm stack, the
        process world — keeps working across the shrink.  Only the
        world comm is rebindable: sub-communicators borrow the dead
        world's sockets and must be re-derived on the new world.

        Note the hash contract: a shrink changes ``size()``, so cached
        jaxprs keyed on the old shape retrace naturally; a respawn
        keeps rank/size and reuses them (``handle`` resolves per call
        on the callback dispatch route — the FFI fast path is off in
        elastic mode for exactly this reason)."""
        if self._parent is not None:
            raise RuntimeError("only the world communicator is "
                               "rebindable; re-split sub-comms on the "
                               "recovered world")
        self._rank = int(rank)
        self._size = int(size)
        self._coord = coord
        self._handle = handle
        self._split_seq = 0

    def split(self, color: int, key=None):
        """Collective: ranks sharing ``color`` form a new communicator,
        ordered by ``(key, parent rank)`` (``key`` defaults to the parent
        rank). ``color < 0`` opts this rank out and returns None.

        Every member of this comm must call ``split`` at the same program
        point (it is itself a collective over the parent transport).
        """
        from . import bridge

        color = int(color)
        key = self._rank if key is None else int(key)
        self._split_seq += 1  # mirrors the native collective-call counter
        seq = self._split_seq
        handle = bridge.split(self.handle, color, key)
        if handle is None:
            return None
        return WorldComm(
            bridge.comm_rank(handle),
            bridge.comm_size(handle),
            self._coord,
            handle=handle,
            lineage=self._lineage + (seq, color),
            parent=self,
        )

    def dup(self):
        """Collective: same membership, isolated message space (the
        reference's default-comm Clone() hygiene, comm.py:4-11 there)."""
        from . import bridge

        # native dup is split(color=0, key=rank) underneath — mirror its
        # collective-call counter so lineage stays in sync with comm_id
        self._split_seq += 1
        seq = self._split_seq
        handle = bridge.dup(self.handle)
        return WorldComm(
            self._rank,
            self._size,
            self._coord,
            handle=handle,
            lineage=self._lineage + (seq, 0),
            parent=self,
        )

    clone = dup
    Clone = dup
    Split = split

    def topology(self):
        """The discovered :class:`mpi4jax_tpu.topo.Topology` of this
        communicator (connects the mesh on first use), or None — flat
        comm, ``MPI4JAX_TPU_TOPO=off``, or a sub-communicator (topology
        is discovered per WORLD; sub-comms inherit its locality
        implicitly through the split-level arena gating)."""
        from .. import topo

        return topo.get_topology(self.handle)

    def coll_algo(self, op: str, nbytes: int) -> str:
        """Name of the collective algorithm the engine would run for
        ``op`` ("allreduce"/"allgather") at ``nbytes`` on this comm —
        "shm" when the same-host arena fast path serves it, else the
        tune package's table pick (see ``mpi4jax_tpu.tune``)."""
        from .. import tune
        from . import bridge

        code = bridge.coll_algo_for(self.handle, tune.OP_KIND[op],
                                    int(nbytes))
        if code is None:
            # pre-engine .so: no table was installed and no forcing is
            # possible, so what actually runs is the arena (when active)
            # or the built-in heuristic — NOT the tune package's merged
            # table; report honestly
            active, _, _ = bridge.shm_info(self.handle)
            return "shm" if active else tune.default_algorithm(op, nbytes)
        return tune.ALGO_NAMES.get(code, "auto")

    def __repr__(self):
        kind = "WorldComm" if self._parent is None else "SubComm"
        return f"{kind}(rank={self._rank}, size={self._size})"

    def __hash__(self):
        return hash(("mpi4jax_tpu.WorldComm", self._size, self._lineage))

    def __eq__(self, other):
        return (
            isinstance(other, WorldComm)
            and other._size == self._size
            and other._rank == self._rank
            and other._lineage == self._lineage
        )

    def __enter__(self):
        from ..parallel.mesh import _push_comm

        _push_comm(self)
        return self

    def __exit__(self, *exc):
        from ..parallel.mesh import _pop_comm

        _pop_comm(self)
        return False

    @property
    def handle(self) -> int:
        """Native communicator id (connects the TCP mesh on first use)."""
        if self._handle is None:
            from . import bridge

            self._handle = bridge.comm_init(self._rank, self._size,
                                            self._coord, hosts=self._hosts)
        return self._handle

    # -- adopting an existing mpi4py communicator ---------------------

    _from_mpi_seq = 0

    @classmethod
    def from_mpi(cls, mpi_comm):
        """Adopt an ``mpi4py`` communicator (any ``MPI.Comm``, including
        ``Split``/``Create``-derived sub-communicators and Cartesian
        topologies' base comms).

        mpi4py is used ONLY for bootstrap — rank/size, per-rank host
        exchange, and base-port agreement; all data then moves over this
        framework's native transport (TCP mesh + same-host shm arena).
        The reference passes ``MPI.Comm`` handles straight into libmpi
        (utils.py:80-127 there); here the comm's *group* is mirrored
        onto a fresh world, which composes with ``split``/``dup`` as
        usual.  Every member of ``mpi_comm`` must call ``from_mpi`` at
        the same program point (it is collective over ``mpi_comm``).

        Per-rank reachable addresses default to 127.0.0.1 (same-host);
        multi-host jobs set ``MPI4JAX_TPU_HOST`` per rank.
        """
        rank = mpi_comm.Get_rank()
        size = mpi_comm.Get_size()
        my_host = os.environ.get("MPI4JAX_TPU_HOST", "127.0.0.1")
        hosts = mpi_comm.allgather(my_host)
        base_port = mpi_comm.bcast(
            _free_port_block(size) if rank == 0 else None, root=0)
        cls._from_mpi_seq += 1  # same order on every member: collective
        return cls(
            rank=rank, size=size,
            coord=f"{hosts[0]}:{base_port}",
            lineage=(0, "mpi", cls._from_mpi_seq, size),
            hosts=",".join(hosts),
        )
