"""Process launcher for world-tier (multi-process) jobs.

The reference has no launcher — users run ``mpirun -n N python prog.py``
(README.rst there).  This framework ships its own:

    python -m mpi4jax_tpu.runtime.launch -n 4 prog.py [args...]

Each rank becomes one process with ``MPI4JAX_TPU_RANK``/``SIZE``/``COORD``
set; ``get_default_comm()`` then returns the :class:`WorldComm`.  Fail-fast:
if any rank exits nonzero, the rest are terminated and the launcher exits
with that code (the job-teardown role MPI_Abort plays in the reference).
"""

from __future__ import annotations

import argparse
import os
import signal
import subprocess
import sys
import time


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.runtime.launch",
        description="run a program as N world-tier ranks",
    )
    parser.add_argument("-n", "--np", type=int, required=True,
                        help="number of ranks")
    parser.add_argument("--port", type=int, default=None,
                        help="base TCP port (default: derived from pid)")
    parser.add_argument("--platform", default=None,
                        help="JAX_PLATFORMS for the ranks (default: cpu)")
    parser.add_argument("--hosts", default=None,
                        help="comma-separated per-rank host list for the "
                             "native transport (pod/DCN layout; default: "
                             "all ranks on 127.0.0.1). Rank i listens on "
                             "hosts[i]; peers dial it there. NOTE: this "
                             "launcher always spawns every rank locally "
                             "(the list is for multi-homed hosts and "
                             "loopback-alias testing); on a real pod, "
                             "start one process per rank with your "
                             "scheduler and set MPI4JAX_TPU_RANK/SIZE "
                             "plus MPI4JAX_TPU_HOSTS directly.")
    parser.add_argument("prog", help="python program to run")
    parser.add_argument("args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.hosts:
        nhosts = len(args.hosts.split(","))
        if nhosts != args.np:
            parser.error(
                f"--hosts lists {nhosts} entries for {args.np} ranks"
            )

    base_port = args.port or (40000 + os.getpid() % 20000)
    # job-unique token for /dev/shm arena names: a crashed earlier job
    # with the same port must never collide with this one's segments
    import uuid

    jobid = uuid.uuid4().hex[:16]
    procs = []
    for rank in range(args.np):
        env = dict(os.environ)
        env["MPI4JAX_TPU_RANK"] = str(rank)
        env["MPI4JAX_TPU_SIZE"] = str(args.np)
        env["MPI4JAX_TPU_COORD"] = f"127.0.0.1:{base_port}"
        env["MPI4JAX_TPU_JOBID"] = jobid
        if args.hosts:
            env["MPI4JAX_TPU_HOSTS"] = args.hosts
        if args.platform:
            env["JAX_PLATFORMS"] = args.platform
        else:
            env.setdefault("JAX_PLATFORMS", "cpu")
        procs.append(
            subprocess.Popen(
                [sys.executable, args.prog, *args.args], env=env
            )
        )

    exit_code = 0
    try:
        while procs:
            for p in list(procs):
                rc = p.poll()
                if rc is None:
                    continue
                procs.remove(p)
                if rc != 0:
                    exit_code = rc
                    # fail-fast: take the rest of the job down
                    for q in procs:
                        q.terminate()
                    deadline = time.time() + 5
                    for q in procs:
                        try:
                            q.wait(timeout=max(0.1, deadline - time.time()))
                        except subprocess.TimeoutExpired:
                            q.kill()
                    procs.clear()
                    break
            time.sleep(0.02)
    except KeyboardInterrupt:
        for q in procs:
            q.send_signal(signal.SIGINT)
        exit_code = 130
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
