"""Process launcher for world-tier (multi-process) jobs.

The reference has no launcher — users run ``mpirun -n N python prog.py``
(README.rst there).  This framework ships its own:

    python -m mpi4jax_tpu.runtime.launch -n 4 prog.py [args...]

Each rank becomes one process with ``MPI4JAX_TPU_RANK``/``SIZE``/``COORD``
set; ``get_default_comm()`` then returns the :class:`WorldComm`.

Failure detection & teardown (the job-reaper role MPI_Abort + the mpirun
supervisor play in the reference):

- **fail-fast**: if any rank exits nonzero, the rest are SIGTERMed (then
  SIGKILLed after a grace period) and the launcher exits with that code,
  printing a one-line post-mortem naming the first-failing rank and its
  last native transport error;
- **--timeout**: a wall-clock watchdog — when the job outlives it, the
  whole rank group is reaped (SIGTERM -> SIGKILL) and the launcher exits
  124, so a wedged job can never hang a scheduler slot forever;
- **SIGTERM** (scheduler preemption) is forwarded to every rank and the
  group is reaped before the launcher exits 143 — no orphan ranks;
- **Ctrl-C** forwards SIGINT, waits a grace period, then escalates to
  SIGTERM/SIGKILL and reaps (exit 130).

The grace period between escalation steps is ``MPI4JAX_TPU_LAUNCH_GRACE_S``
(default 5 seconds).

**Elastic mode** (``--elastic [--elastic-policy shrink|respawn]``,
docs/elasticity.md)
replaces fail-fast with recovery supervision: a dead rank advances the
world *generation* — the launcher announces the survivor map and a
re-derived port block as ``gen_<n>.json`` in a coordination directory
(``MPI4JAX_TPU_ELASTIC_DIR``), survivors rebuild through
``mpi4jax_tpu.elastic.recover()``, and under the ``respawn`` policy the
dead slot's program is restarted in a fresh process that joins the new
bootstrap.  A job that finishes after recoveries exits 0; the
post-mortem then names the recovery outcome (generation reached, slots
lost, resume step) instead of a first-failing rank.
"""

from __future__ import annotations

import argparse
import collections
import os
import signal
import subprocess
import sys
import threading
import time


def _grace_s() -> float:
    try:
        return max(0.1, float(os.environ.get("MPI4JAX_TPU_LAUNCH_GRACE_S",
                                             "5")))
    except ValueError:
        return 5.0


class _Terminated(Exception):
    """Raised by the SIGTERM handler to unwind into the reap path."""


def _pump_stderr(pipe, tail):
    """Forward one rank's stderr verbatim, keeping a tail for the
    post-mortem.  Verbatim matters: peers' transport diagnostics and the
    debug-trace format must reach the launcher's stderr unchanged."""
    try:
        for line in iter(pipe.readline, b""):
            tail.append(line)
            try:
                sys.stderr.buffer.write(line)
                sys.stderr.buffer.flush()
            except Exception:
                pass
    finally:
        try:
            pipe.close()
        except Exception:
            pass


def _self_heal_outcomes(slot_tails):
    """Scan every slot's stderr tail for link-layer self-healing lines
    (native/tpucomm.cc's greppable contract).  Returns
    ``({slot: recovered_count}, [(slot, peer), ...])`` — slots that
    healed a transient link fault IN PLACE (they are not dead and must
    not be reported as deaths), and links the layer declared DEAD after
    exhausting MPI4JAX_TPU_RETRY (naming the failed connection is the
    post-mortem's job; the exit code alone cannot)."""
    import re as _re

    healed = {}
    dead_links = []
    for slot, tail in sorted(slot_tails.items()):
        for line in tail:
            raw = bytes(line)
            if _re.search(rb"self-heal: link to r\d+ recovered", raw):
                healed[slot] = healed.get(slot, 0) + 1
            m = _re.search(rb"self-heal: link to r(\d+) DEAD", raw)
            if m:
                dead_links.append((slot, int(m.group(1))))
    return healed, dead_links


def _last_native_error(tail):
    """The most recent transport diagnostic in a rank's stderr tail."""
    for line in reversed(tail):
        text = line.decode(errors="replace").strip()
        if "tpucomm" in text or "returned error code" in text:
            return text
    for line in reversed(tail):
        text = line.decode(errors="replace").strip()
        if text:
            return text
    return ""


def _terminate_group(procs, grace=None):
    """SIGTERM every live rank, wait up to the grace period, SIGKILL the
    stragglers, and reap everything — no orphans survive this call."""
    grace = _grace_s() if grace is None else grace
    live = [p for p in procs if p.poll() is None]
    for p in live:
        try:
            p.terminate()
        except OSError:
            pass
    deadline = time.time() + grace
    for p in live:
        try:
            p.wait(timeout=max(0.1, deadline - time.time()))
        except subprocess.TimeoutExpired:
            try:
                p.kill()
            except OSError:
                pass
            p.wait()


def _preflight_verify(prog: str, np_: int, prog_args=()) -> int:
    """Run the static communication verifier on ``prog`` before spawning
    any rank.  Returns 0 to proceed; 3 (with the findings table on
    stderr) when verification fails; the analyzer's own code on analyzer
    errors.

    Runs as a subprocess on purpose: the launcher itself imports no jax,
    and a verifier crash must not take the launcher down with it.
    """
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env.setdefault("PYTHONPATH", repo)
    # warnings document assumptions and do not block a launch; the "--"
    # keeps the program's own flags out of the analyzer's parser
    res = subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.analyze", prog,
         "--np", str(np_), "--errors-only", "--", *prog_args],
        capture_output=True, text=True, env=env,
    )
    if res.returncode == 0:
        if "WARNING" in res.stdout:
            print(f"[launch] --verify: {prog} has warnings at np={np_} "
                  "(launch proceeds):", file=sys.stderr)
            sys.stderr.write(res.stdout)
        else:
            print(f"[launch] --verify: {prog} clean at np={np_}",
                  file=sys.stderr)
        sys.stderr.flush()
        return 0
    if res.returncode == 3:
        print(f"[launch] --verify FAILED for {prog} at np={np_} — "
              "no rank was spawned:", file=sys.stderr)
        sys.stderr.write(res.stdout)
        sys.stderr.write(res.stderr)
        sys.stderr.flush()
        return 3
    print(f"[launch] --verify could not run the analyzer "
          f"(exit {res.returncode}):", file=sys.stderr)
    sys.stderr.write(res.stderr[-2000:])
    sys.stderr.flush()
    return res.returncode or 2


def _emit_plan_at(prog: str, np_: int, prog_args, plan_path: str):
    """One analyzer --emit-plan run at a specific world size; returns
    the CompletedProcess (the caller interprets exit codes)."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env.setdefault("PYTHONPATH", repo)
    return subprocess.run(
        [sys.executable, "-m", "mpi4jax_tpu.analyze", prog,
         "--np", str(np_), "--errors-only", "--emit-plan", plan_path,
         "--", *prog_args],
        capture_output=True, text=True, env=env,
    )


def _bundle_shrink_ladder(prog: str, np_: int, prog_args,
                          plan_path: str, primary: dict) -> None:
    """Elastic-safe plans: extend the verified primary plan into a
    *bundle* carrying one verified plan per world size a shrinking job
    may pass through (np-1 .. 2).  ``bridge.rebuild`` then re-derives
    and re-proves the surviving size's plan inside recovery instead of
    dropping the overlap.  Sizes whose plan cannot be compiled/proved
    are skipped with a notice — recovery at those sizes runs the
    historic path.  The bundle overwrites ``plan_path`` in place (the
    MPI4JAX_TPU_PLAN export is unchanged); the wire format is
    ``analysis/_plan.py``'s plan-bundle/1."""
    import json as _json
    import tempfile

    plans = {str(np_): primary}
    skipped = []
    for n2 in range(np_ - 1, 1, -1):
        fd, sub_path = tempfile.mkstemp(prefix="m4j_plan_",
                                        suffix=".json")
        os.close(fd)
        try:
            res = _emit_plan_at(prog, n2, prog_args, sub_path)
            if res.returncode not in (0, 3):
                skipped.append((n2, f"analyzer exit {res.returncode}"))
                continue
            with open(sub_path) as f:
                sub = _json.load(f)
            if not (sub.get("proved") and sub.get("rewritten")):
                why = ("not proved" if not sub.get("proved")
                       else "unrewritten")
                skipped.append((n2, why))
                continue
            plans[str(n2)] = sub
        except Exception as e:
            skipped.append((n2, str(e)))
        finally:
            try:
                os.unlink(sub_path)
            except OSError:
                pass
    try:
        # one source of truth for the wire format; the literals below
        # only serve the run-as-a-plain-file mode (no package context)
        from ..analysis._plan import BUNDLE_FORMAT, BUNDLE_VERSION
    except ImportError:
        BUNDLE_FORMAT, BUNDLE_VERSION = "plan-bundle", 1
    bundle = {
        "format": BUNDLE_FORMAT,
        "version": BUNDLE_VERSION,
        "analyzer_version": primary.get("analyzer_version", ""),
        "plans": plans,
    }
    tmp = f"{plan_path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        _json.dump(bundle, f, indent=1, sort_keys=True)
    os.replace(tmp, plan_path)
    covered = sorted(int(n) for n in plans)
    print(f"[launch] --plan --elastic: plan bundle covers "
          f"np={covered} — a shrink inside this range re-proves and "
          "keeps its plan", file=sys.stderr, flush=True)
    for n2, why in skipped:
        print(f"[launch] --plan --elastic: no plan for np={n2} ({why}); "
              "a shrink to that size runs the historic path",
              file=sys.stderr, flush=True)


def _preflight_plan(prog: str, np_: int, prog_args=(),
                    enforce_verify: bool = False, elastic: bool = False):
    """Compile + verify ``prog``'s execution plan before spawning any
    rank (the schedule compiler, docs/analysis.md § "From verifier to
    compiler").  Returns ``(rc, plan_path)``: nonzero ``rc`` aborts the
    launch (only possible with ``enforce_verify``, which folds the
    ``--verify`` gate into this single analyzer run instead of tracing
    the program twice); an empty ``plan_path`` means no plan should be
    installed — compile failure, an unproved plan, or an unrewritten
    one (exporting a trivial plan would cost the FFI fast path and
    per-op bookkeeping for zero overlap benefit) — and the job runs the
    historic token-order path, which is always correct.

    ``elastic`` additionally compiles the shrink ladder into a plan
    BUNDLE (see :func:`_bundle_shrink_ladder`) so recovery keeps the
    overlap."""
    import tempfile

    fd, plan_path = tempfile.mkstemp(prefix="m4j_plan_", suffix=".json")
    os.close(fd)
    res = _emit_plan_at(prog, np_, prog_args, plan_path)
    if res.returncode == 3 and enforce_verify:
        print(f"[launch] --verify FAILED for {prog} at np={np_} — "
              "no rank was spawned:", file=sys.stderr)
        sys.stderr.write(res.stdout)
        sys.stderr.write(res.stderr)
        sys.stderr.flush()
        os.unlink(plan_path)
        return 3, ""
    if enforce_verify and res.returncode == 0 and "WARNING" in res.stdout:
        # same surfacing contract as plain --verify: warnings document
        # assumptions and must not get quieter because --plan rode along
        print(f"[launch] --verify: {prog} has warnings at np={np_} "
              "(launch proceeds):", file=sys.stderr)
        sys.stderr.write(res.stdout)
        sys.stderr.flush()
    if res.returncode not in (0, 3):
        if enforce_verify:
            print(f"[launch] --verify could not run the analyzer "
                  f"(exit {res.returncode}):", file=sys.stderr)
            sys.stderr.write(res.stderr[-2000:])
            sys.stderr.flush()
            os.unlink(plan_path)
            return res.returncode or 2, ""
        print(f"[launch] --plan: schedule compiler could not run "
              f"(exit {res.returncode}); running without a plan:",
              file=sys.stderr)
        sys.stderr.write(res.stderr[-2000:])
        sys.stderr.flush()
        os.unlink(plan_path)
        return 0, ""
    try:
        import json as _json

        with open(plan_path) as f:
            plan = _json.load(f)
        proved = bool(plan.get("proved"))
        rewritten = bool(plan.get("rewritten"))
        reasons = plan.get("reasons", [])
    except Exception as e:
        print(f"[launch] --plan: cannot read compiled plan: {e}",
              file=sys.stderr, flush=True)
        os.unlink(plan_path)
        return 0, ""
    if not (proved and rewritten):
        state = "NOT proved equivalent" if not proved else "unrewritten"
        print(f"[launch] --plan: plan for {prog} at np={np_} is "
              f"{state}; running without a plan:"
              + "".join(f"\n    {r}" for r in reasons),
              file=sys.stderr, flush=True)
        os.unlink(plan_path)
        return 0, ""
    print(f"[launch] --plan: verified plan "
          f"{plan.get('cache_key', '?')} for {prog} at np={np_}"
          + "".join(f"\n    note: {r}" for r in reasons),
          file=sys.stderr, flush=True)
    if elastic:
        _bundle_shrink_ladder(prog, np_, prog_args, plan_path, plan)
    return 0, plan_path


def _merge_trace(out_path: str, np_: int) -> None:
    """Merge the per-rank recordings into one Perfetto-loadable Chrome
    trace at ``out_path``.  Best effort — a failed job may have dumped
    only some parts, and a partial timeline still beats none (the merge
    reports how many ranks it found)."""
    import json

    try:
        from .. import obs  # stdlib-only import (no jax)
    except ImportError:  # executed as a plain file (no package context)
        import importlib.util

        _obs_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "obs")
        _spec = importlib.util.spec_from_file_location(
            "m4j_obs_launch", os.path.join(_obs_dir, "__init__.py"),
            submodule_search_locations=[_obs_dir])
        obs = importlib.util.module_from_spec(_spec)
        sys.modules["m4j_obs_launch"] = obs
        _spec.loader.exec_module(obs)

    parts = obs.part_paths(out_path)
    if not parts:
        print(f"launch: --trace: no recordings found at "
              f"{out_path}.rank*.json (did the ranks reach comm init?)",
              file=sys.stderr, flush=True)
        return
    try:
        merged = obs.merge_files(parts)
        with open(out_path, "w") as f:
            json.dump(merged, f)
        spans = sum(1 for e in merged["traceEvents"] if e.get("ph") == "X")
        print(f"launch: --trace: merged {len(parts)}/{np_} rank "
              f"recording(s), {spans} spans -> {out_path} "
              "(load in https://ui.perfetto.dev)",
              file=sys.stderr, flush=True)
    except Exception as e:
        print(f"launch: --trace: merge failed: {e}", file=sys.stderr,
              flush=True)


def main(argv=None):
    parser = argparse.ArgumentParser(
        prog="python -m mpi4jax_tpu.runtime.launch",
        description="run a program as N world-tier ranks",
    )
    parser.add_argument("-n", "--np", type=int, required=True,
                        help="number of ranks")
    parser.add_argument("--port", type=int, default=None,
                        help="base TCP port (default: derived from pid)")
    parser.add_argument("--platform", default=None,
                        help="JAX_PLATFORMS for the ranks (default: cpu)")
    parser.add_argument("--timeout", type=float, default=None,
                        help="wall-clock watchdog: SIGTERM (then SIGKILL) "
                             "the whole rank group after this many seconds "
                             "and exit 124 — a wedged job is reaped, not "
                             "inherited by the scheduler")
    parser.add_argument("--hosts", default=None,
                        help="comma-separated per-rank host list for the "
                             "native transport (pod/DCN layout; default: "
                             "all ranks on 127.0.0.1). Rank i listens on "
                             "hosts[i]; peers dial it there. NOTE: this "
                             "launcher always spawns every rank locally "
                             "(the list is for multi-homed hosts and "
                             "loopback-alias testing); on a real pod, "
                             "start one process per rank with your "
                             "scheduler and set MPI4JAX_TPU_RANK/SIZE "
                             "plus MPI4JAX_TPU_HOSTS directly.")
    parser.add_argument("--fake-hosts", default=None, metavar="SPEC",
                        help="virtual host partition for topology testing "
                             "(exports MPI4JAX_TPU_FAKE_HOSTS to every "
                             "rank): 'r0,r1|r2,r3' makes ranks 0-1 and "
                             "2-3 two islands — intra-island shm arenas, "
                             "TCP between islands, hierarchical "
                             "collectives eligible (docs/usage.md "
                             "§ Transport tiers and topology)")
    parser.add_argument("--verify", action="store_true",
                        help="pre-flight: statically verify the program's "
                             "communication schedule (python -m "
                             "mpi4jax_tpu.analyze) and exit 3 with the "
                             "findings table when it fails — BEFORE any "
                             "rank is spawned")
    parser.add_argument("--plan", action="store_true",
                        help="pre-flight: compile the program's "
                             "communication schedule into a verified "
                             "execution plan (python -m mpi4jax_tpu."
                             "analyze --emit-plan) and run every rank "
                             "with MPI4JAX_TPU_PLAN pointing at it — "
                             "hoisted recv posts and deferred send "
                             "completions on the progress engine.  An "
                             "unprovable plan falls back to the "
                             "historic path with a notice "
                             "(docs/analysis.md)")
    parser.add_argument("--elastic", action="store_true",
                        help="supervise for RECOVERY instead of "
                             "fail-fast teardown: a dead rank advances "
                             "the world generation — survivors rebuild "
                             "over a re-derived port block via "
                             "mpi4jax_tpu.elastic.recover(), and under "
                             "the respawn policy the dead slot's "
                             "program restarts in a fresh process.  A "
                             "job that completes after recoveries "
                             "exits 0 (docs/elasticity.md)")
    parser.add_argument("--elastic-policy", default=None,
                        choices=("shrink", "respawn"),
                        help="what --elastic does about a dead rank: "
                             "shrink (default; survivors renumber "
                             "densely into a smaller world) or respawn "
                             "(restart the dead slot at full size).  "
                             "Default: MPI4JAX_TPU_ELASTIC_POLICY, "
                             "else shrink")
    parser.add_argument("--trace", default=None, metavar="OUT.json",
                        help="record every rank's per-op events "
                             "(MPI4JAX_TPU_TRACE) and merge them into one "
                             "Perfetto-loadable Chrome trace at OUT.json "
                             "after the job ends; per-rank recordings stay "
                             "next to it as OUT.json.rank<r>.json "
                             "(docs/observability.md)")
    parser.add_argument("--live", action="store_true",
                        help="arm live drift detection + collective "
                             "re-tuning in every rank "
                             "(MPI4JAX_TPU_LIVE=auto; thresholds via "
                             "MPI4JAX_TPU_LIVE_WINDOW / _DRIFT_PCT / "
                             "_COOLDOWN_OPS — docs/usage.md)")
    parser.add_argument("prog", help="python program to run")
    parser.add_argument("args", nargs=argparse.REMAINDER)
    args = parser.parse_args(argv)

    if args.hosts:
        nhosts = len(args.hosts.split(","))
        if nhosts != args.np:
            parser.error(
                f"--hosts lists {nhosts} entries for {args.np} ranks"
            )

    plan_path = ""
    if args.plan:
        # one analyzer run serves both gates: with --verify it enforces
        # the findings verdict too (tracing a large program twice would
        # double the pre-launch cost for nothing)
        rc, plan_path = _preflight_plan(args.prog, args.np, args.args,
                                        enforce_verify=args.verify,
                                        elastic=args.elastic)
        if rc != 0:
            return rc
    elif args.verify:
        rc = _preflight_verify(args.prog, args.np, args.args)
        if rc != 0:
            return rc

    if args.trace:
        # stale parts from a previous run at the same path (possibly a
        # different world size) must not leak into this run's merge or
        # into tune --from-trace's glob
        import glob as _glob

        trace_abs = os.path.abspath(args.trace)
        for stale in _glob.glob(f"{_glob.escape(trace_abs)}.rank*.json"):
            try:
                os.unlink(stale)
            except OSError:
                pass

    base_port = args.port or (40000 + os.getpid() % 20000)
    # job-unique token for /dev/shm arena names: a crashed earlier job
    # with the same port must never collide with this one's segments
    import uuid

    jobid = uuid.uuid4().hex[:16]

    elastic_policy = None
    elastic_dir = None
    if args.elastic:
        elastic_policy = (args.elastic_policy
                          or os.environ.get("MPI4JAX_TPU_ELASTIC_POLICY")
                          or "shrink").strip()
        if elastic_policy not in ("shrink", "respawn"):
            parser.error(
                f"--elastic policy must be shrink or respawn, "
                f"got {elastic_policy!r}")
        import tempfile

        elastic_dir = tempfile.mkdtemp(prefix="m4j_elastic_")

    procs = []
    tails = []
    pumps = []

    # scheduler preemption (SIGTERM to the launcher) must take the whole
    # rank group down, not orphan it — installed BEFORE the first spawn
    # so a signal landing mid-startup still reaches the reap path.
    # During the spawn loop itself delivery is DEFERRED, not raised: a
    # handler firing between Popen() returning and procs.append() would
    # otherwise reap a group missing the just-forked rank.  (Blocking
    # the signals with pthread_sigmask instead is wrong: children
    # inherit the blocked mask through fork+exec and would then never
    # see forwarded signals at all.)
    in_spawn = [True]
    deferred = []

    def _on_sigterm(signum, frame):
        if in_spawn[0]:
            deferred.append(_Terminated)
        else:
            raise _Terminated

    def _on_sigint_spawn(signum, frame):
        deferred.append(KeyboardInterrupt)

    old_term = signal.signal(signal.SIGTERM, _on_sigterm)
    old_int = signal.getsignal(signal.SIGINT)

    exit_code = 0
    first_fail = None  # (rank, exit code)
    watchdog_fired = False
    t_start = time.time()
    pending = {}       # slot -> live process
    slot_tails = {}    # slot -> the slot's LATEST process's stderr tail
    generation = 0
    deaths = []        # every rank death observed, in order
    lost_slots = []    # slots PERMANENTLY lost (shrink; respawned
                       # slots died but are back, so they are not lost)
    # a deterministically-crashing program under respawn would otherwise
    # loop forever; past this many generations the launcher gives up
    max_generations = 2 * args.np + 2

    def _spawn(slot, *, rank, size, coord, gen):
        """One rank process; returns its Popen.  ``slot`` is the
        launcher-slot identity (stable across generations), ``rank``
        the world rank this process bootstraps with."""
        env = dict(os.environ)
        env["MPI4JAX_TPU_RANK"] = str(rank)
        env["MPI4JAX_TPU_SIZE"] = str(size)
        env["MPI4JAX_TPU_COORD"] = coord
        env["MPI4JAX_TPU_JOBID"] = jobid
        if elastic_policy is not None:
            env["MPI4JAX_TPU_ELASTIC"] = "1"
            env["MPI4JAX_TPU_ELASTIC_DIR"] = elastic_dir
            env["MPI4JAX_TPU_ELASTIC_POLICY"] = elastic_policy
            env["MPI4JAX_TPU_GENERATION"] = str(gen)
            env["MPI4JAX_TPU_SLOT"] = str(slot)
            # recovery depends on every blocking wait being bounded:
            # poison frames unblock most peers instantly, but a peer
            # parked on the DEAD rank's socket needs the deadline.
            # setdefault — explicit operator settings win.
            env.setdefault("MPI4JAX_TPU_TIMEOUT_S", "60")
            env.setdefault("MPI4JAX_TPU_CONNECT_TIMEOUT_S", "60")
        if args.trace:
            env["MPI4JAX_TPU_TRACE"] = os.path.abspath(args.trace)
        if args.live:
            env["MPI4JAX_TPU_LIVE"] = "auto"
        if plan_path:
            env["MPI4JAX_TPU_PLAN"] = plan_path
        if args.hosts:
            env["MPI4JAX_TPU_HOSTS"] = args.hosts
        if args.fake_hosts:
            env["MPI4JAX_TPU_FAKE_HOSTS"] = args.fake_hosts
        if args.platform:
            env["JAX_PLATFORMS"] = args.platform
        else:
            env.setdefault("JAX_PLATFORMS", "cpu")
        p = subprocess.Popen(
            [sys.executable, args.prog, *args.args], env=env,
            stderr=subprocess.PIPE,
        )
        tail = collections.deque(maxlen=80)
        pump = threading.Thread(
            target=_pump_stderr, args=(p.stderr, tail), daemon=True
        )
        pump.start()
        procs.append(p)
        tails.append(tail)
        pumps.append(pump)
        slot_tails[slot] = tail
        return p

    def _announce(gen, members, port, policy):
        """Atomically write the generation file survivors poll for:
        member map (slot -> dense new rank; lost slots -> -1), world
        size, and the re-derived base port."""
        mapping = {str(s): i for i, s in enumerate(members)}
        for s in lost_slots:
            mapping.setdefault(str(s), -1)
        hosts = ""
        if args.hosts:
            hl = args.hosts.split(",")
            hosts = ",".join(hl[s] for s in members)
        spec = {
            "generation": gen,
            "size": len(members),
            "base_port": port,
            "map": mapping,
            "lost": list(lost_slots),
            "policy": policy,
            "hosts": hosts,
            "np0": args.np,
        }
        path = os.path.join(elastic_dir, f"gen_{gen}.json")
        import json as _json

        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            _json.dump(spec, f)
        os.replace(tmp, path)
        return spec

    def _failover(slot, rc):
        """One dead rank under --elastic: record it, advance the
        generation, announce the survivor map, and (respawn policy)
        restart the dead slot's program.  Returns False when recovery
        is impossible (no survivors / generation cap) — the caller
        then falls back to fail-fast semantics."""
        nonlocal generation
        deaths.append(slot)
        if elastic_policy != "respawn":
            lost_slots.append(slot)
        generation += 1
        live = sorted(pending)
        err = _last_native_error(slot_tails.get(slot, ()))
        print(
            f"launch: elastic: rank slot {slot} died (exit code {rc})"
            + (f"; last error: {err}" if err else "")
            + f"; advancing to generation {generation} "
            f"({elastic_policy})", file=sys.stderr, flush=True,
        )
        if generation > max_generations:
            print(
                f"launch: elastic: giving up after {generation - 1} "
                "recoveries (generation cap); tearing the job down",
                file=sys.stderr, flush=True,
            )
            return False
        if not live and elastic_policy == "shrink":
            print(
                "launch: elastic: no surviving rank to shrink onto",
                file=sys.stderr, flush=True,
            )
            return False
        new_port = base_port + generation * (args.np + 1)
        if elastic_policy == "respawn":
            members = sorted(set(live) | {slot})
            spec = _announce(generation, members, new_port, "respawn")
            new_rank = spec["map"][str(slot)]
            in_spawn[0] = True
            try:
                p = _spawn(slot, rank=new_rank, size=len(members),
                           coord=f"127.0.0.1:{new_port}",
                           gen=generation)
            finally:
                in_spawn[0] = False
            pending[slot] = p
            if deferred:
                raise deferred[0]
        else:
            _announce(generation, live, new_port, "shrink")
        return True

    try:
        signal.signal(signal.SIGINT, _on_sigint_spawn)
        for rank in range(args.np):
            pending[rank] = _spawn(
                rank, rank=rank, size=args.np,
                coord=f"127.0.0.1:{base_port}", gen=0)
        in_spawn[0] = False
        signal.signal(signal.SIGINT, old_int)
        if deferred:
            raise deferred[0]  # a signal arrived mid-spawn: reap now
        while pending:
            dead = []
            for slot, p in list(pending.items()):
                rc = p.poll()
                if rc is not None:
                    dead.append((slot, rc))
            if any(rc != 0 for _, rc in dead):
                # cascade failures land milliseconds after their root
                # cause: a victim polled late in the sweep could be
                # seen dead while the root cause (already exited, but
                # polled earlier, while still alive) waits for the
                # next sweep — misattributing "failed first".  One
                # short beat + re-poll collects the whole failure
                # wave before attribution.
                time.sleep(0.08)
                for slot, p in list(pending.items()):
                    rc = p.poll()
                    if rc is not None and (slot, rc) not in dead:
                        dead.append((slot, rc))
            aborted = False
            for slot, rc in sorted(dead):
                if slot not in pending:
                    continue
                del pending[slot]
                if rc == 0:
                    continue
                if first_fail is None:
                    first_fail = (slot, rc)
                if elastic_policy is not None and _failover(slot, rc):
                    continue
                exit_code = rc
                # fail-fast: take the rest of the job down
                _terminate_group(list(pending.values()))
                pending.clear()
                aborted = True
                break
            if aborted:
                break
            if pending and args.timeout is not None \
                    and time.time() - t_start > args.timeout:
                watchdog_fired = True
                stuck = sorted(s for s, p in pending.items()
                               if p.poll() is None)
                print(
                    f"launch: watchdog: wall-clock timeout after "
                    f"{args.timeout:g} s; terminating rank(s) {stuck}",
                    file=sys.stderr, flush=True,
                )
                _terminate_group(list(pending.values()))
                pending.clear()
                exit_code = 124
            time.sleep(0.02)
    except KeyboardInterrupt:
        # repeated signals must not unwind the reap itself: ignore both
        # for the remainder of the teardown
        signal.signal(signal.SIGINT, signal.SIG_IGN)
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        live = [p for p in procs if p.poll() is None]
        for p in live:
            try:
                p.send_signal(signal.SIGINT)
            except OSError:
                pass
        # grace, then escalate: no orphan ranks survive Ctrl-C
        deadline = time.time() + _grace_s()
        for p in live:
            try:
                p.wait(timeout=max(0.1, deadline - time.time()))
            except subprocess.TimeoutExpired:
                pass
        _terminate_group(live)
        signal.signal(signal.SIGINT, old_int)
        exit_code = 130
    except _Terminated:
        # a re-delivered SIGTERM (schedulers re-signal) or a Ctrl-C
        # during the grace wait must not raise inside this very handler
        # and abort the reap half-way
        signal.signal(signal.SIGTERM, signal.SIG_IGN)
        signal.signal(signal.SIGINT, signal.SIG_IGN)
        _terminate_group(procs)
        signal.signal(signal.SIGINT, old_int)
        exit_code = 143
    except Exception:
        # e.g. a Popen failure mid-spawn: already-forked ranks must not
        # outlive the launcher's own crash
        _terminate_group(procs)
        raise
    finally:
        signal.signal(signal.SIGINT, old_int)
        signal.signal(signal.SIGTERM, old_term)
        for pump in pumps:
            pump.join(timeout=2.0)
        if plan_path:  # every exit path, not just straight-line success
            try:
                os.unlink(plan_path)
            except OSError:
                pass

    if args.trace:
        _merge_trace(os.path.abspath(args.trace), args.np)

    # link-layer self-healing outcomes from every slot's stderr tail: a
    # slot that RECOVERED a transient link fault in place is not a dead
    # rank, and must never be reported as one; a link the layer declared
    # DEAD names the failed connection (slot -> peer) for the
    # post-mortem, since the dying rank's exit code alone cannot
    healed_slots, dead_links = _self_heal_outcomes(slot_tails)
    heal_note = ""
    if healed_slots:
        total = sum(healed_slots.values())
        heal_note = (
            f"; transient link fault(s) healed in-place on rank slot(s) "
            f"{sorted(healed_slots)} ({total} reconnect(s), not rank "
            f"deaths)")
    link_note = ""
    if dead_links:
        link_note = "; failed link(s): " + ", ".join(
            f"rank {s} -> rank {p}" for s, p in dead_links)

    if elastic_policy is not None and generation > 0 and exit_code == 0:
        # the recovery outcome, not the first failure: the job SURVIVED
        # — say what it cost and where it resumed (exit code stays 0)
        import re as _re

        steps = []
        for tail in slot_tails.values():
            for line in tail:
                m = _re.search(rb"resum\w+ from step (\d+)",
                               bytes(line))
                if m:
                    steps.append(int(m.group(1)))
        resume = f", resumed from step {max(steps)}" if steps else \
            ", no checkpoint resume reported"
        # shrink loses slots permanently; a respawned slot died but
        # finished — saying "lost" for it would misread the outcome
        outcome = (f"lost rank slot(s) {lost_slots}"
                   if elastic_policy != "respawn" else
                   f"rank death(s) at slot(s) {deaths} (respawned)")
        print(
            f"launch: post-mortem: elastic job completed after recovery "
            f"(policy {elastic_policy}): reached generation "
            f"{generation}, {outcome}{resume}{link_note}{heal_note}",
            file=sys.stderr, flush=True,
        )
    elif first_fail is not None:
        rank, rc = first_fail
        err = _last_native_error(slot_tails.get(rank, ()))
        gen_note = (
            f" after reaching generation {generation} "
            f"(death(s) at slot(s) {deaths})"
            if elastic_policy is not None and generation > 0 else "")
        print(
            f"launch: post-mortem: rank {rank} failed first (exit code "
            f"{rc}){gen_note}" + (f"; last error: {err}" if err else "")
            + link_note + heal_note,
            file=sys.stderr, flush=True,
        )
    elif healed_slots:
        # the job SUCCEEDED and nothing died, but the wire was not
        # quiet: say what the link layer absorbed, so a flaky fabric is
        # visible before it degrades into actual rank deaths
        print(
            "launch: post-mortem: job completed; no rank failed"
            + heal_note.replace("; ", " — ", 1),
            file=sys.stderr, flush=True,
        )
    elif watchdog_fired:
        print(
            "launch: post-mortem: no rank failed — the job outlived the "
            f"--timeout watchdog ({args.timeout:g} s); a hung transport "
            "wait with MPI4JAX_TPU_TIMEOUT_S unset looks exactly like "
            "this (docs/sharp-bits.md)",
            file=sys.stderr, flush=True,
        )
    if elastic_dir is not None:
        import shutil

        shutil.rmtree(elastic_dir, ignore_errors=True)
    return exit_code


if __name__ == "__main__":
    sys.exit(main())
