"""Runtime consumption of verified execution plans (schedule compiler).

The analysis-side schedule compiler (``analysis/_plan.py``) turns a
statically-extracted per-rank schedule into an :class:`ExecutionPlan`
whose equivalence the match simulator has proven.  This module executes
it: a :class:`PlanRunner` installed on a communicator shadows the op
stream the host executors see and, where the plan licenses it,

- **pre-posts hoisted receives** — at the plan's post point the recv's
  descriptor goes onto the progress engine as a non-blocking ticket
  (``bridge.post_recv``), so the wire drains into the user buffer while
  the host is still computing; the recv's own callback then merely waits
  the ticket;
- **defers send completions** — sends past the buffered-send threshold
  post as tickets (``bridge.post_send``) instead of parking the callback
  until the wire write finishes; the wait happens lazily at the next
  synchronous op (FIFO: by then it costs nothing).  Sends at or below
  the threshold keep the native detached path, which also preserves
  their coalescing eligibility;
- leaves everything else exactly on the historic path, in exact
  program order (the engine queue drains FIFO, so wire order never
  deviates from what the prover verified).

Safety: the runner matches every runtime op against the plan's op
signatures.  Any mismatch — a program whose runtime schedule diverges
from the verified static schedule — permanently disables the plan for
that communicator (loudly), drains every outstanding ticket, and falls
back to direct execution.  ``MPI4JAX_TPU_PLAN=0`` (or unset) keeps this
module entirely inert: one module-level boolean guards the hot path.

Import-light by design (numpy + stdlib + the jax-free analysis plan
module): bridge-level world programs can exercise plan execution in any
container, the same contract the PR 5 coalescing tests rely on.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional

import numpy as np

#: handle -> PlanRunner; empty = every hook is one boolean check
_runners: Dict[int, "PlanRunner"] = {}
_active = False


def _plan_mod():
    from ..analysis import _plan

    return _plan


_spec_cache = False  # False = unresolved (None is a valid resolution)


def plan_spec() -> Optional[str]:
    """The raw MPI4JAX_TPU_PLAN value, or None when plan execution is
    off (unset, empty, or an explicit falsy value — the pre-plan
    behavior, bit-for-bit).  Resolved once: the knob is a job-level
    setting (the launcher exports it before any rank starts), and this
    sits on the per-op hot path."""
    global _spec_cache
    if _spec_cache is False:
        raw = os.environ.get("MPI4JAX_TPU_PLAN", "").strip()
        _spec_cache = None \
            if not raw or raw.lower() in ("0", "false", "off", "no") \
            else raw
    return _spec_cache


def active() -> bool:
    return _active


def get(comm) -> Optional["PlanRunner"]:
    """The runner serving ``comm``, or None.

    With plan execution off (no runner installed, MPI4JAX_TPU_PLAN
    unset) this is one module-global check plus one env read — it never
    touches ``comm.handle``, so AbstractComms under analysis are safe.
    With MPI4JAX_TPU_PLAN set but no runner yet, reading ``comm.handle``
    deliberately triggers lazy communicator creation, whose comm_init
    hook installs the runner — otherwise the FIRST op of a job would
    slip past the plan (WorldComm handles are created on first use)."""
    if _active:
        try:
            return _runners.get(comm.handle)
        except Exception:
            return None
    if plan_spec() is None:
        return None
    try:
        handle = comm.handle  # lazy comm creation installs the runner
    except Exception:
        return None
    return _runners.get(handle)


def install(handle: int, plan, rank: int) -> bool:
    """Attach a verified plan's per-rank schedule to a communicator.

    Refuses (False, with a warning) anything the runner cannot execute
    faithfully: unproven plans, a missing rank, or a native library
    without the ticketed posting entry."""
    global _active
    from . import bridge

    rp = plan.ranks.get(rank)
    if rp is None:
        _warn(f"plan {plan.cache_key} has no schedule for rank {rank}")
        return False
    if not plan.proved:
        _warn(f"plan {plan.cache_key} was not proved equivalent; "
              "refusing to execute it")
        return False
    if any(tuple(op.comm) != (0,) for op in rp.ops):
        # the compiler leaves sub-comm schedules unrewritten; a plan
        # file carrying sub-comm ops anyway (hand-edited, stale) would
        # desync this world-comm cursor — refuse it
        _warn(f"plan {plan.cache_key} contains sub-communicator ops; "
              "the runner serves the world communicator only")
        return False
    if not bridge.post_available():
        _warn("native library predates ticketed posting (tpucomm_post); "
              "rebuild native/ to execute schedule plans")
        return False
    _runners[int(handle)] = PlanRunner(int(handle), plan, rp)
    _active = True
    return True


def maybe_install_from_env(handle: int, rank: int, size: int) -> None:
    """``bridge.comm_init`` hook: when MPI4JAX_TPU_PLAN names a plan
    file (the ``launch --plan`` wiring) — or a plan *bundle* (one
    verified plan per survivable world size, what ``launch --plan
    --elastic`` emits) — load the entry serving this world size and
    attach this rank's schedule to the world communicator.  Never
    fatal — a bad plan file degrades to the historic path with a
    warning, it must not take a healthy job down."""
    spec = plan_spec()
    if spec is None or spec.lower() in ("1", "true", "on", "yes", "auto"):
        return  # bare enable: plans attach via the API / plan cache
    try:
        plan = _plan_mod().load_plan_for_size(spec, size)
    except Exception as err:
        _warn(f"cannot load MPI4JAX_TPU_PLAN={spec}: {err}")
        return
    if plan is None:
        _warn(f"MPI4JAX_TPU_PLAN={spec} holds no plan for np={size}; "
              "ignoring it")
        return
    install(handle, plan, rank)


#: elastic-safe plan source: ``world_size -> ExecutionPlan | (events_by_
#: rank, comms) | None``.  Registered by programs that install plans via
#: the API (:func:`set_plan_source`); the env-spec (file/bundle) path
#: needs no registration — :func:`reinstall_after_rebuild` reads
#: MPI4JAX_TPU_PLAN itself.
_plan_source = None


def set_plan_source(fn) -> None:
    """Register how to re-derive this job's plan for a NEW world size
    (elastic recovery).  ``fn(world_size)`` returns an
    :class:`ExecutionPlan` compiled for that size (it will be
    re-proved before installation), a ``(events_by_rank, comms)`` pair
    to compile fresh, or None (no plan for that size).  Pass ``None``
    to unregister."""
    global _plan_source
    _plan_source = fn


def drop(handle: int) -> None:
    """Forget a communicator's runner WITHOUT flushing its tickets —
    the rebuild path, where the old world's sockets are already dead
    and a ticket wait would hang on them."""
    global _active
    _runners.pop(int(handle), None)
    if not _runners:
        _active = False


def reinstall_after_rebuild(old_handle, handle: int, rank: int,
                            size: int) -> bool:
    """Elastic recovery's plan step (called from ``bridge.rebuild``):
    drop the dead world's runner, re-derive the plan for the NEW world
    size, re-PROVE it through the equivalence prover, and install it —
    so a recovered job keeps its overlap instead of silently losing it
    (docs/elasticity.md § Plans survive recovery).

    The plan for the new size comes from the registered
    :func:`set_plan_source` callback, or from the MPI4JAX_TPU_PLAN
    file/bundle.  Whatever the source, nothing executes without a
    fresh proof: a stored plan is recompiled from its own schedule
    (``_plan.recompile_plan``) and its cache key must survive the
    round trip (the signature check).  Every outcome is loud.  Returns
    True when a re-proved plan is active on the new world."""
    if old_handle:
        drop(old_handle)
    spec = plan_spec()
    source = _plan_source
    if source is None and (
            spec is None
            or spec.lower() in ("1", "true", "on", "yes", "auto")):
        return False  # no plan was driving this job
    plan_mod = _plan_mod()
    stored = None
    try:
        if source is not None:
            stored = source(size)
        else:
            stored = plan_mod.load_plan_for_size(spec, size)
    except Exception as err:
        _warn(f"cannot re-derive a plan for the recovered np={size} "
              f"world: {err}; continuing on the historic path")
        return False
    if stored is None:
        _warn(f"no plan available for the recovered np={size} world "
              "(the bundle/source does not cover this size); "
              "continuing on the historic path")
        return False
    try:
        if isinstance(stored, tuple):
            events_by_rank, comms = stored
            fresh = plan_mod.compile_schedules(events_by_rank, comms,
                                               world_size=size)
        else:
            fresh = plan_mod.recompile_plan(stored)
            if fresh.cache_key != stored.cache_key:
                _warn(f"re-derived plan signature {fresh.cache_key} does "
                      f"not match the stored plan {stored.cache_key} for "
                      f"np={size}; refusing it — the file does not "
                      "contain the schedule it claims to")
                return False
    except Exception as err:
        _warn(f"plan re-derivation failed for np={size}: {err}; "
              "continuing on the historic path")
        return False
    if fresh.world_size != size:
        _warn(f"re-derived plan is for np={fresh.world_size}, the "
              f"recovered world is np={size}; refusing it")
        return False
    if not fresh.proved:
        _warn(f"re-derived plan for np={size} failed its re-proof:"
              + "".join(f"\n    {r}" for r in fresh.reasons)
              + "\n  continuing on the historic path")
        return False
    if not install(handle, fresh, rank):
        return False
    _warn(f"re-proved plan {fresh.cache_key} for the recovered "
          f"np={size} world ({fresh.proof.get('interleavings', 0)} "
          "interleavings re-verified); overlap preserved across "
          "recovery")
    return True


def detach(handle: int) -> None:
    """Drain and remove a communicator's runner (finalize path)."""
    global _active
    rt = _runners.pop(int(handle), None)
    if rt is not None:
        rt.flush()
    if not _runners:
        _active = False


def _warn(msg: str) -> None:
    print(f"[plan] {msg}", file=sys.stderr, flush=True)


#: cap on outstanding tickets per runner: bounds buffer keep-alive
#: memory; FIFO means waiting the oldest is effectively free by the
#: time the cap is reached
MAX_OUTSTANDING = 16


class PlanRunner:
    """Executes one rank's verified plan against the live op stream."""

    def __init__(self, handle: int, plan, rank_plan):
        self.handle = handle
        self.plan = plan
        self.ops = rank_plan.ops
        self.cursor = 0
        self.enabled = True
        # post_point -> positions of hoisted recvs posted right after it
        self.hoists_after: Dict[int, List[int]] = {}
        for pos, op in enumerate(self.ops):
            if op.kind == "recv" and op.post_at < pos:
                self.hoists_after.setdefault(op.post_at, []).append(pos)
        self.preposted: Dict[int, tuple] = {}   # pos -> (ticket, out, ka)
        self.outstanding: List[tuple] = []      # (ticket, ka, pool_buf)
        # pooled payload-copy buffers for deferred sends, keyed by
        # (dtype, shape): the callback's operand ndarray aliases
        # XLA-owned storage that dies with the callback, so the posted
        # descriptor needs a copy we own — and a FRESH multi-MB buffer
        # per op costs page faults that would eat the overlap win
        # (glibc returns big frees to the kernel immediately), so the
        # copies recycle through this pool as their tickets complete
        self._send_pool: Dict[tuple, List[np.ndarray]] = {}
        # pooled pre-post recv buffers, same page-fault rationale.  A
        # served buffer is recycled in TWO steps: it lands in
        # ``_recv_recycle_pending`` when returned to the caller and
        # only moves to the pool at the NEXT runner entry — by then the
        # serving host callback has finished and XLA has copied the
        # result out, so the engine may write into the storage again.
        self._recv_pool: Dict[tuple, List[np.ndarray]] = {}
        self._recv_recycle_pending: List[np.ndarray] = []
        self.stats = {"hoisted_recvs": 0, "deferred_sends": 0,
                      "mismatches": 0}

    # -- bookkeeping ----------------------------------------------------

    def _drain(self) -> None:
        from . import bridge

        while self.outstanding:
            ticket, _ka, pool_buf = self.outstanding.pop(0)
            bridge.wait_ticket(self.handle, ticket)
            if pool_buf is not None:
                free = self._send_pool.setdefault(
                    (pool_buf.dtype, pool_buf.shape), [])
                if len(free) < MAX_OUTSTANDING:
                    free.append(pool_buf)

    def flush(self) -> None:
        """Wait everything outstanding (finalize / disable path)."""
        from . import bridge

        self._drain()
        for pos in sorted(self.preposted):
            ticket, _out, _ka = self.preposted.pop(pos)
            bridge.wait_ticket(self.handle, ticket)

    def _disable(self, why: str) -> None:
        self.enabled = False
        self.stats["mismatches"] += 1
        _warn(
            f"runtime op stream diverged from plan "
            f"{self.plan.cache_key} at position {self.cursor} ({why}); "
            "plan execution disabled for this communicator — the job "
            "continues on the historic path"
        )
        # outstanding sends are real posted work: wait them out.  A
        # pre-posted recv cannot be cancelled; it is consumed by the
        # next matching direct recv (see run_recv's disabled path).
        # The planner refuses hoists on channels that also carry
        # Status/wildcard receives, so that reconciliation covers every
        # plannable schedule — but say so loudly if tickets remain.
        if self.preposted:
            chans = sorted(
                {(self.ops[p].source, self.ops[p].tag)
                 for p in self.preposted})
            _warn(
                f"{len(self.preposted)} pre-posted receive ticket(s) "
                f"remain outstanding on (source, tag) {chans}; they own "
                "the next wire message on their channels and will be "
                "consumed by the next matching receive.  If this job "
                "misbehaves, rerun with MPI4JAX_TPU_PLAN=0."
            )
        self._drain()

    def _flush_recycle(self) -> None:
        while self._recv_recycle_pending:
            buf = self._recv_recycle_pending.pop()
            free = self._recv_pool.setdefault((buf.dtype, buf.shape), [])
            if len(free) < MAX_OUTSTANDING:
                free.append(buf)

    def _advance(self) -> None:
        from . import bridge

        pos = self.cursor
        for hoist_pos in self.hoists_after.get(pos, ()):
            if hoist_pos in self.preposted or not self.enabled:
                continue
            op = self.ops[hoist_pos]
            key = (np.dtype(op.dtype), tuple(op.shape or ()))
            free = self._recv_pool.get(key)
            out = free.pop() if free else np.empty(key[1], key[0])
            ticket, ka = bridge.post_recv_into(self.handle, out,
                                               op.source, op.tag)
            self.preposted[hoist_pos] = (ticket, out, ka)
            self.stats["hoisted_recvs"] += 1
        self.cursor = pos + 1
        if self.cursor >= len(self.ops):
            # plan cycle complete (steady-state jit loop): flush every
            # deferred completion, then rearm for the next iteration
            self._drain()
            self.cursor = 0

    def _expect(self, kind: str, **sig) -> Optional[object]:
        """The plan op at the cursor if it matches the runtime op's
        signature, else None (after disabling)."""
        if self.cursor >= len(self.ops):
            self.cursor = 0
        op = self.ops[self.cursor]
        if op.kind != kind:
            self._disable(f"expected {op.kind}, saw {kind}")
            return None
        for name, value in sig.items():
            want = getattr(op, name)
            if want is not None and value is not None and want != value:
                self._disable(
                    f"{kind}.{name}: plan has {want!r}, runtime has "
                    f"{value!r}")
                return None
        return op

    # -- op entry points (called from the ops-layer host executors) -----

    def run_send(self, buf: np.ndarray, dest: int, tag: int,
                 owned: bool = False) -> bool:
        """Returns True when the send was posted (deferred completion);
        False = caller must execute the historic path.

        ``owned=True`` is the MPI_Isend buffer contract: the caller
        guarantees ``buf``'s storage stays valid and unmodified until
        the runner's next drain point (the next recv/sync op, plan
        wrap, or flush), and the post skips the payload copy.  The
        ops-layer callback path must NOT claim ownership — its operand
        arrays alias XLA-owned storage that dies with the callback."""
        from . import bridge

        if not self.enabled:
            return False
        self._flush_recycle()
        op = self._expect("send", dest=dest, tag=tag, nbytes=buf.nbytes)
        if op is None:
            return False
        if not op.deferred or buf.nbytes <= self.plan.detach_threshold:
            # the native detached path already buffers small sends (and
            # keeps them coalescible); no ticket needed
            bridge.send(self.handle, buf, dest, tag)
            self._advance()
            return True
        if len(self.outstanding) >= MAX_OUTSTANDING:
            ticket, _ka, pool_buf = self.outstanding.pop(0)
            bridge.wait_ticket(self.handle, ticket)
            if pool_buf is not None:
                self._send_pool.setdefault(
                    (pool_buf.dtype, pool_buf.shape), []).append(pool_buf)
        if owned:
            wire_buf, pool_buf = buf, None
        else:
            # copy into a pooled buffer we own: the caller's ndarray
            # may alias XLA-owned callback storage that dies when the
            # callback returns, while the ticket outlives it (see
            # bridge.post_send's ownership contract)
            free = self._send_pool.get((buf.dtype, buf.shape))
            wire_buf = free.pop() if free else np.empty_like(buf)
            np.copyto(wire_buf, buf)
            pool_buf = wire_buf
        ticket, ka = bridge.post_send(self.handle, wire_buf, dest, tag)
        self.outstanding.append((ticket, ka, pool_buf))
        self.stats["deferred_sends"] += 1
        self._advance()
        return True

    def run_recv(self, shape, dtype, source: int, tag: int,
                 reuse: bool = False):
        """The received array when the runner served the recv (possibly
        from a pre-posted ticket), else None."""
        from . import bridge

        shape = tuple(shape)
        dtype = np.dtype(dtype)
        nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize \
            if shape else dtype.itemsize
        if not self.enabled:
            # consume a matching pre-posted descriptor left over from
            # before the mismatch: its ticket owns the next message on
            # this channel, so the direct path must not race it
            for pos, (ticket, out, _ka) in sorted(self.preposted.items()):
                pop = self.ops[pos]
                if (pop.source == source and pop.tag == tag
                        and out.nbytes == nbytes):
                    del self.preposted[pos]
                    bridge.wait_ticket(self.handle, ticket)
                    if out.shape == shape and out.dtype == dtype:
                        return out
                    return np.frombuffer(
                        out.tobytes(), dtype=dtype).reshape(shape).copy()
            return None
        # dtype/shape are part of the signature: matching on byte count
        # alone would let a stale plan's pre-posted buffer be silently
        # bit-reinterpreted (f32[64] plan vs i32[64] runtime)
        self._flush_recycle()
        op = self._expect("recv", source=source, tag=tag, nbytes=nbytes,
                          dtype=str(dtype), shape=shape)
        if op is None:
            return None
        pos = self.cursor
        if pos in self.preposted:
            ticket, out, _ka = self.preposted.pop(pos)
            bridge.wait_ticket(self.handle, ticket)
            if reuse:
                # callback-path contract (same as bridge._reused_out):
                # the result is copied out of our buffer before the
                # next host op runs, so it may recycle then
                self._recv_recycle_pending.append(out)
        else:
            out = bridge.recv(self.handle, shape, dtype, source, tag,
                              reuse=reuse)
        # a completed recv proves every earlier ticket on this FIFO
        # engine is done: collect them now (frees the EngineOps and
        # recycles the send-copy pool; each wait returns instantly)
        self._drain()
        self._advance()
        return out

    def run_sync(self, kind: str, execute, **sig):
        """Every other op: verify against the plan, run the historic
        path, then collect completed tickets (FIFO: the synchronous op
        queued behind them, so every earlier ticket is already done).
        ``execute`` is a zero-arg closure running the real op."""
        if not self.enabled:
            return execute()
        self._flush_recycle()
        op = self._expect(kind, **sig)
        if op is None:
            return execute()
        result = execute()
        self._drain()
        self._advance()
        return result
