"""The ICI data plane of the hierarchical schedules (``hring``/``htree``).

PR 10's topology subsystem routes the intra-island phase of a
hierarchical allreduce over the native shm arena or TCP; on a TPU slice
that leaves the one physically fastest wire — inter-chip ICI — out of
the data plane.  This module promotes ``ops/pallas_collectives.py``
from a mesh-tier novelty into that data plane: when every member of an
island sits on an ici-tier TPU slice (or ``MPI4JAX_TPU_ICI_LEG=force``),
the intra-island leg of an f32 SUM allreduce runs as the fused Pallas
ring — double-buffered async remote DMA, the next hop in flight while
the current chunk folds — and, under quantized wire formats
(``MPI4JAX_TPU_COLL_QUANT=force``), the island sum is packed to the
native int8 wire frame IN KERNEL (``quant_pack_pallas``, bit-compatible
with ``tpucomm_quant_pack``) so the leader leg exchanges pre-quantized
bytes with no host-side pack/unpack.

Dispatch contract (hooked from ``bridge.allreduce_raw`` BEFORE both
native paths, so the descriptor/io_uring fast path is bypassed only
when the leg actually runs):

- f32 SUM only — every other (dtype, op) falls through to the native
  schedules untouched (they are association-free there anyway);
- the resolved algorithm must be ``hring``/``htree`` (explicitly forced
  or the engine's own pick via ``coll_algo_for``) on a multi-island
  comm with cached sub-comms, ``MPI4JAX_TPU_HIER`` not ``deny`` (deny
  must keep degrading to the flat twins) and plan execution off;
- ``auto`` additionally requires EVERY multi-member island to be
  ici-tier: the leg exchanges different frames than the native intra
  paths, so a half-activated world would deadlock — all or nothing;
  ``force`` skips only the tier check (the off-TPU dryrun/tier-1 axis).

Schedule (phases mirror the native ``hier_allreduce``):

1. intra: allgather the members' payloads over the intra sub-comm and
   fold them with the ring association — the Pallas fused-ring kernel
   when jax >= 0.6 and enough local devices are present, else its
   bit-identical numpy twin (``simulate_ring_sum``'s arithmetic; the
   kernel is verified against it in interpret mode).  Either way the
   result is EXACTLY ``topo.simulate_hring_sum(..., intra="ring")``'s
   phase 1;
2. leaders: exact mode forces the flat ``ring``/``rd`` twin of the
   requested hierarchical algorithm over the leaders sub-comm; quant
   mode allgathers the once-packed int8 frames (lossless) and EVERY
   leader dequantize-folds them in island order in f32 — one qdq per
   contribution and a rank-consistent fold order by construction
   (``topo.simulate_ici_q_sum`` is the bit-exact model);
3. intra bcast of the leader's bytes (identical on every rank).

The schedule signature stays plain ``allreduce`` — the verifier, golden
plans and analysis cache keys never see the leg, exactly as PRs 8/10
kept their upgrades below the plan layer.

Observability: the intra leg emits ONE ops-src span with ``tier="ici"``
(name ``Allreduce``, the leg's payload bytes) nested inside the whole-op
record; ``obs.stats()`` attributes it in the tier rows / ``tier_bytes``
while the tuner's ``_usable_trace_event`` keeps ignoring tier-carrying
events — zero double-counting either way.
"""

from __future__ import annotations

import time
from typing import Optional

import numpy as np

from ..utils import config

#: native wire codes (native/tpucomm.h) — the leg is f32 SUM only
_F32, _SUM = 11, 0

_BACKEND: Optional[str] = None
_RING_CACHE: dict = {}
_PACK_CACHE: dict = {}


def _pallas_ready() -> bool:
    """Can the fused Pallas kernels actually run here (jax >= 0.6 with
    the Pallas remote-DMA API importable)?  Resolved once; when False
    the leg runs its bit-identical numpy twin instead, so bridge-level
    worlds exercise the same schedule in ANY container."""
    try:
        import jax

        parts = []
        for piece in jax.__version__.split(".")[:3]:
            parts.append(int("".join(c for c in piece if c.isdigit()) or 0))
        if tuple(parts) < (0, 6, 0):
            return False
        from ..ops import pallas_collectives  # noqa: F401

        return True
    except Exception:
        return False


def ici_leg_backend() -> str:
    """``"pallas"`` (fused kernels, interpret mode off-TPU) or
    ``"numpy"`` (the bit-identical twin) — resolved once per process."""
    global _BACKEND
    if _BACKEND is None:
        _BACKEND = "pallas" if _pallas_ready() else "numpy"
    return _BACKEND


def _quant_mod():
    from . import _simulate

    return _simulate._quant_refs()


def _ring_sum_numpy(rows: np.ndarray) -> np.ndarray:
    from . import _simulate

    return _simulate.simulate_ring_sum([rows[i] for i in range(len(rows))])


def _ring_sum_pallas(rows: np.ndarray) -> np.ndarray:
    """The fused kernel over ``m`` local devices (row i on device i);
    every device finishes with identical bits, row 0 is returned."""
    import jax
    import jax.numpy as jnp
    from jax import shard_map
    from jax.sharding import PartitionSpec as P

    from ..ops import pallas_collectives as pc

    m, count = rows.shape
    key = (m, count)
    fn = _RING_CACHE.get(key)
    if fn is None:
        mesh = jax.make_mesh((m,), ("ici",),
                             devices=jax.devices()[:m])
        fn = jax.jit(shard_map(
            lambda v: pc.fused_ring_allreduce_sum(v.reshape(-1), "ici")[
                None],
            mesh=mesh, in_specs=P("ici"), out_specs=P("ici")))
        _RING_CACHE[key] = fn
    out = fn(jnp.asarray(rows, jnp.float32))
    return np.asarray(jax.device_get(out)[0])


def _island_ring_sum(rows: np.ndarray) -> np.ndarray:
    """(m, count) f32 member rows -> the island sum every member holds,
    with the EXACT ``simulate_ring_sum`` association either way."""
    if ici_leg_backend() == "pallas":
        try:
            import jax

            if len(jax.devices()) >= rows.shape[0]:
                return _ring_sum_pallas(rows)
        except Exception:
            pass
    return _ring_sum_numpy(rows)


def _pack_numpy(island: np.ndarray) -> np.ndarray:
    return _quant_mod().quant_pack_wire_ref(island)


def _pack_pallas(island: np.ndarray) -> np.ndarray:
    import jax

    from ..ops import pallas_collectives as pc

    fn = _PACK_CACHE.get(island.size)
    if fn is None:
        fn = jax.jit(pc.quant_pack_pallas)
        _PACK_CACHE[island.size] = fn
    return np.asarray(jax.device_get(fn(island)))


def _pack(island: np.ndarray) -> np.ndarray:
    """The native int8 wire frame of the island sum (scale bytes then
    codes) — in-kernel when the Pallas backend is live, else the numpy
    codec reference; bit-identical by (test-enforced) contract."""
    if ici_leg_backend() == "pallas":
        try:
            return _pack_pallas(island)
        except Exception:
            pass
    return _pack_numpy(island)


def _unpack_fold(frames: np.ndarray, order, count: int) -> np.ndarray:
    """Dequantize the leaders' wire frames and fold them in island
    order, f32 throughout (``simulate_ici_q_sum``'s exact arithmetic —
    no final re-quantization)."""
    q = _quant_mod()
    nb = -(-count // q.QUANT_BLOCK)
    acc = None
    for row in order:
        frame = frames[row]
        scales = frame[:4 * nb].copy().view(np.float32)
        codes = frame[4 * nb:]
        d = q.quant_unpack_ref(scales, codes)
        acc = d if acc is None else (acc + d).astype(np.float32)
    return acc


def eligible(t, *, mode: Optional[str] = None) -> bool:
    """Topology-level eligibility (the per-call dtype/op/algo gates live
    in :func:`maybe_allreduce`): multi-island, hier not denied, plan
    execution off, and — under ``auto`` — every multi-member island
    fully ici-tier."""
    mode = mode or config.ici_leg_mode()
    if mode == "off" or t is None or not t.multi:
        return False
    if config.hier_mode() == "deny":
        return False
    if config.plan_spec() is not None:
        return False
    if mode == "force":
        return True
    return all(all(t.tiers[r] == "ici" for r in members)
               for members in t.islands if len(members) > 1)


def ici_leg_status(handle=None) -> dict:
    """Resolved leg status for diagnostics: ``{"mode", "backend",
    "active"}`` — ``active`` is the topology-level eligibility of
    ``handle`` (False without one)."""
    from . import get_topology

    mode = config.ici_leg_mode()
    t = get_topology(handle) if handle is not None else None
    return {
        "mode": mode,
        "backend": ici_leg_backend(),
        "active": bool(t is not None and eligible(t, mode=mode)),
    }


def ici_leg_active(handle) -> bool:
    return ici_leg_status(handle)["active"]


def _record_leg(algo_name: str, t0: float, dur: float, nbytes: int) -> None:
    try:
        from ..obs import _recorder

        if _recorder.enabled():
            _recorder.record_span("Allreduce", t0, dur, nbytes=nbytes,
                                  algo=algo_name, tier="ici")
    except Exception:
        pass


def maybe_allreduce(handle, buf, out, dtype_code: int, op_code: int,
                    algo) -> bool:
    """Run the ICI-leg schedule for this ``allreduce_raw`` call if it is
    eligible; returns True when ``out`` has been filled (the caller
    returns immediately), False to fall through to the native paths.

    Ineligibility is always a QUIET fallthrough — the strict knob
    parser is the loud guard; a world where some ranks run the leg and
    others don't cannot happen because every gate below is a function
    of rank-agreed state (env knobs, the shared topology, the forced
    algo code)."""
    mode = config.ici_leg_mode()
    if mode == "off":
        return False
    if dtype_code != _F32 or op_code != _SUM:
        return False
    from .. import tune
    from ..runtime import bridge

    sub = bridge._topo_subcomms.get(int(handle))
    if sub is None:
        return False
    t = sub["topology"]
    if not eligible(t, mode=mode):
        return False
    code = int(algo or 0)
    if not code:
        try:
            code = int(bridge.coll_algo_for(handle, 0, buf.nbytes))
        except Exception:
            return False
    if code == tune.ALGO_CODES["hring"]:
        algo_name, leader_algo = "hring", tune.ALGO_CODES["ring"]
    elif code == tune.ALGO_CODES["htree"]:
        algo_name, leader_algo = "htree", tune.ALGO_CODES["rd"]
    else:
        return False
    if buf.dtype != np.float32:
        return False

    rank = sub["rank"]
    members = t.islands[sub["island"]]
    m = len(members)
    quant = config.quant_mode() == "force"

    # ---- phase 1: the ICI intra leg -------------------------------
    t0 = time.time()
    if m > 1:
        rows = bridge.allgather(sub["intra"], buf.reshape(-1), m)
        island = _island_ring_sum(np.ascontiguousarray(rows, np.float32))
    else:
        island = np.ascontiguousarray(buf, np.float32).reshape(-1).copy()
    packed = _pack(island) if quant else None
    _record_leg(algo_name, t0, time.time() - t0, buf.nbytes)

    # ---- phase 2: the leader leg ----------------------------------
    L = t.n_islands
    # leader-comm rank r is the r-th smallest leader world rank; the
    # fold below must run in ISLAND order (the simulator's contract)
    leader_order = sorted(range(L), key=lambda i: t.leaders[i])
    res = None
    if sub["leader"] is not None:
        if quant:
            frames = bridge.allgather(sub["leader"], packed, L)
            by_island = {isl: r for r, isl in enumerate(leader_order)}
            res = _unpack_fold(frames, [by_island[i] for i in range(L)],
                               island.size)
        else:
            res = bridge.allreduce(sub["leader"], island, _SUM,
                                   algo=leader_algo)

    # ---- phase 3: intra bcast of the leader's bytes ---------------
    if m > 1:
        root = members.index(t.leaders[sub["island"]])
        res = bridge.bcast(sub["intra"],
                           res if res is not None else island, root)
    np.copyto(out, np.asarray(res).reshape(out.shape).astype(np.float32,
                                                             copy=False))
    return True
