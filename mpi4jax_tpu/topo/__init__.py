"""Topology subsystem: locality discovery and the hierarchical tiers.

The world tier's transport is flat by default — at np8 split across two
hosts the ring crosses the slow TCP boundary on every hop even though
six of the eight rank pairs share a shm arena.  This package makes
locality explicit:

- **discovery** (:func:`discover`, run by ``runtime.bridge.comm_init``
  at communicator creation): every rank contributes a host fingerprint
  (hostname + boot id, TPU chip sniff, and the
  ``MPI4JAX_TPU_FAKE_HOSTS`` virtual partition for single-machine
  testing) through a bootstrap allgather, and the agreeing result
  becomes a :class:`Topology`;
- **sub-communicators**: on a multi-island world the bridge derives an
  intra-island comm and a leaders comm through the existing ``split``
  machinery, caches them per world comm, and installs the map natively
  (``tpucomm_set_topology``) so the transport's dispatch is
  locality-aware;
- **hierarchical collectives**: the native engine's ``hring``/``htree``
  schedules (intra-island shm reduce → leader-tier TCP allreduce —
  the only leg eligible for the ``qring``/``qrd`` quantized wire
  formats under ``MPI4JAX_TPU_COLL_QUANT=force`` — → intra-island
  bcast), first-class rows in the tune decision table, plus
  hierarchical routing for large ``bcast``/``reduce``;
- **transport tiers** ``ici > shm > tcp``: each rank's best tier is
  reported per link (:meth:`Topology.link`), ``ici`` marking ranks
  backed by a live TPU mesh (device collectives ride
  ``lax.psum``/Pallas on that tier — see docs/usage.md).

Knobs (``utils/config.py`` is the registry): ``MPI4JAX_TPU_TOPO``
(auto/off discovery), ``MPI4JAX_TPU_FAKE_HOSTS`` (virtual partition),
``MPI4JAX_TPU_HIER`` (allow/deny/force hierarchical schedules).

This module is importable without jax, numpy, or the native library
(pure stdlib), like ``tune``; only :func:`discover` and the numpy
schedule simulators (lazy re-exports from ``_simulate``) need more.
"""

from __future__ import annotations

import hashlib
import json
import os
import socket
from typing import Dict, List, Optional, Sequence, Tuple

FINGERPRINT_VERSION = 1

#: transport tier names, best first (the promotion order of the
#: ROADMAP's "ici > shm > tcp" pillar)
TIERS = ("ici", "shm", "tcp")

QUANT_BLOCK = 256  # codec block (native tpucomm_quant_packed_bytes)


def _quant_wire_bytes(nbytes: int) -> int:
    """On-wire bytes of an ``nbytes`` f32 payload under the int8+scales
    codec: one int8 code per element plus one f32 scale per 256-element
    block — mirrors ``bridge.quant_packed_bytes`` without loading the
    native library."""
    count = nbytes // 4
    return count + 4 * ((count + QUANT_BLOCK - 1) // QUANT_BLOCK)


def parse_fake_hosts(spec: Optional[str], size: int) -> Optional[List[Optional[str]]]:
    """Parse ``MPI4JAX_TPU_FAKE_HOSTS`` (``r0,r1|r2,r3``: groups of
    world ranks separated by ``|``, tokens ``rN`` or bare ``N``) into a
    per-rank virtual host label, ``None`` for unlisted ranks — or
    ``None`` when the spec is empty.  Mirrors the native parser
    byte-for-byte: malformed tokens and duplicate ranks raise (loud,
    like the fault spec — a typo'd partition must not silently test
    the wrong shape); out-of-range ranks are ignored, so a spec
    written for np=4 stays valid on a shrunk np=2 world."""
    if not spec or not spec.strip():
        return None
    labels: List[Optional[str]] = [None] * size
    seen = set()
    for group_idx, group in enumerate(spec.split("|")):
        for tok in group.split(","):
            tok = tok.strip()
            if not tok:
                continue
            body = tok[1:] if tok[:1] in ("r", "R") else tok
            try:
                r = int(body)
            except ValueError:
                r = -1
            if r < 0 or (body and not body.isdigit()):
                raise ValueError(
                    f"cannot parse MPI4JAX_TPU_FAKE_HOSTS token {tok!r} "
                    "(expected rN or N, groups separated by |)")
            if r < size:
                # duplicates are tracked for IN-RANGE ranks only, like
                # the native parser: a spec written for a larger world
                # may repeat ranks the shrunk world no longer has
                if r in seen:
                    raise ValueError(
                        f"MPI4JAX_TPU_FAKE_HOSTS lists rank {r} twice")
                seen.add(r)
                labels[r] = f"fake-host-{group_idx}"
    return labels


def synthetic_islands(world_size: int, n_islands: int
                      ) -> Tuple[List[List[int]], str]:
    """A contiguous equal-split island map for virtual-scale testing:
    ``(islands, fake_hosts_spec)`` where ``islands`` is the member-rank
    lists in island order (the shape ``Topology.islands`` and the
    ``simulate_h*`` oracles take) and ``fake_hosts_spec`` is the
    ``MPI4JAX_TPU_FAKE_HOSTS`` string that produces exactly that
    partition under :func:`parse_fake_hosts`.  ``world_size`` must
    split evenly — a synthetic shape that silently dropped ranks
    would test the wrong world."""
    if n_islands < 1 or world_size % n_islands:
        raise ValueError(
            f"cannot split {world_size} ranks into {n_islands} equal "
            "islands")
    per = world_size // n_islands
    islands = [list(range(b, b + per))
               for b in range(0, world_size, per)]
    spec = "|".join(",".join(f"r{r}" for r in members)
                    for members in islands)
    return islands, spec


def _boot_id() -> str:
    """A per-boot host identity: two ranks share a host exactly when
    hostname AND boot id agree (containers can share a hostname string
    without sharing memory; the boot id disambiguates)."""
    try:
        with open("/proc/sys/kernel/random/boot_id") as f:
            return f.read().strip()
    except OSError:
        return ""


def _tpu_chip_count() -> int:
    """Best-effort count of locally attached TPU chips WITHOUT touching
    jax (initializing a backend at comm bootstrap could claim the
    accelerator): the libtpu device-node conventions."""
    count = 0
    for pattern in ("/dev/accel", "/dev/vfio/"):
        base = os.path.dirname(pattern) or "/dev"
        prefix = os.path.basename(pattern)
        try:
            for name in os.listdir(base):
                if prefix and not name.startswith(prefix):
                    continue
                if pattern == "/dev/accel" and name[len(prefix):].isdigit():
                    count += 1
        except OSError:
            pass
        if count:
            break
    return count


def local_fingerprint(rank: int, size: int) -> dict:
    """This rank's host fingerprint — what discovery allgathers."""
    fake = parse_fake_hosts(os.environ.get("MPI4JAX_TPU_FAKE_HOSTS"), size)
    return {
        "v": FINGERPRINT_VERSION,
        "host": socket.gethostname(),
        "boot_id": _boot_id(),
        "fake": fake[rank] if fake else None,
        "tpu_chips": _tpu_chip_count(),
    }


class Topology:
    """The discovered locality map of one world communicator.

    ``islands[i]`` is the sorted member-rank list of island ``i`` (ranks
    sharing a host / shm domain); island ids are dense and ordered by
    each island's lowest rank (its *leader*) — the ordering the native
    hierarchical schedules rely on.  ``tiers[r]`` is rank r's best
    local tier (``ici`` when a live TPU mesh backs it, else ``shm``);
    :meth:`link` classifies a rank pair."""

    def __init__(self, fingerprints: Sequence[dict]):
        self.size = len(fingerprints)
        self.fingerprints = list(fingerprints)
        self.hosts: List[str] = []
        for rank, fp in enumerate(fingerprints):
            key = fp.get("fake") or (
                f"{fp.get('host', '?')}|{fp.get('boot_id', '')}")
            self.hosts.append(str(key))
        order: Dict[str, int] = {}
        self.island_of: List[int] = []
        for rank, key in enumerate(self.hosts):
            if key not in order:
                order[key] = len(order)
            self.island_of.append(order[key])
        self.islands: List[List[int]] = [[] for _ in range(len(order))]
        for rank, isl in enumerate(self.island_of):
            self.islands[isl].append(rank)
        self.leaders = [members[0] for members in self.islands]
        self.tiers = [
            "ici" if int(fp.get("tpu_chips") or 0) > 0 else "shm"
            for fp in fingerprints
        ]

    @property
    def n_islands(self) -> int:
        return len(self.islands)

    @property
    def multi(self) -> bool:
        """True when hierarchical schedules have something to exploit."""
        return self.n_islands > 1

    def island(self, rank: int) -> List[int]:
        return self.islands[self.island_of[rank]]

    def leader(self, rank: int) -> int:
        return self.leaders[self.island_of[rank]]

    def link(self, a: int, b: int) -> str:
        """Transport class of the (a, b) link: ``self``, ``ici`` (both
        ranks TPU-backed on one host — the device mesh tier), ``shm``
        (same island), or ``tcp`` (island boundary)."""
        if a == b:
            return "self"
        if self.island_of[a] != self.island_of[b]:
            return "tcp"
        if self.tiers[a] == "ici" and self.tiers[b] == "ici":
            return "ici"
        return "shm"

    def fingerprint(self) -> str:
        """Stable 12-hex-digit hash of the topology SHAPE (world size,
        island sizes in island order, per-island best tier) — the key
        of the topology-aware persistent tune cache.  Deliberately
        independent of hostnames: two deployments with the same shape
        share tuning."""
        shape = {
            "v": 1,
            "size": self.size,
            "islands": [len(m) for m in self.islands],
            "tiers": [
                min((self.tiers[r] for r in members),
                    key=TIERS.index)
                for members in self.islands
            ],
        }
        blob = json.dumps(shape, sort_keys=True).encode()
        return hashlib.sha256(blob).hexdigest()[:12]

    def leg_bytes(self, algo: str, nbytes: int) -> Dict[str, int]:
        """Analytic per-job wire-byte split of one collective of
        ``nbytes`` logical payload under ``algo``: total bytes crossing
        intra-island links vs inter-island (leader-tier) links, summed
        over all ranks.  Flat algorithms put everything on whichever
        links the schedule happens to cross; they are reported as
        ``inter`` when the world spans islands (the pessimal flat
        placement the hierarchy exists to avoid)."""
        n, L = self.size, self.n_islands
        if algo in ("hring", "htree"):
            intra = 2 * nbytes * sum(len(m) - 1 for m in self.islands)
            if L <= 1:
                inter = 0
            elif algo == "hring":
                # ring over the leaders: 2*(L-1)/L of the payload per
                # leader, each way
                inter = 2 * (L - 1) * nbytes
            else:
                # recursive doubling: every butterfly participant sends
                # the FULL payload per round, plus the non-power-of-two
                # fold's lend-and-return pair
                pof2 = 1
                while pof2 * 2 <= L:
                    pof2 *= 2
                rem = L - pof2
                inter = (pof2 * pof2.bit_length() - pof2 + 2 * rem) * nbytes
            return {"intra": int(intra), "inter": int(inter)}
        if algo in ("halltoall", "hqalltoall"):
            # nbytes is the per-rank send buffer; one chunk per peer
            chunk = nbytes // n
            packed = _quant_wire_bytes  # codec arithmetic, f32 elements
            intra = sum(len(m) * (len(m) - 1) for m in self.islands) * chunk
            inter = 0
            for ia, A in enumerate(self.islands):
                for ib, B in enumerate(self.islands):
                    if ia == ib:
                        continue
                    cross = len(A) * len(B) * chunk
                    # leader-tier block: ONE codec frame per (A, B) pair
                    # on the quantized leg
                    inter += (packed(cross) if algo == "hqalltoall"
                              else cross)
                    # staging hops: non-leader members of A hand their
                    # cross chunks to leader_a; leader_b fans out to the
                    # non-leader members of B — always exact bytes
                    intra += (len(A) - 1) * len(B) * chunk
                    intra += len(A) * (len(B) - 1) * chunk
            return {"intra": int(intra), "inter": int(inter)}
        if algo == "qalltoall":
            # flat quantized pairwise exchange: every off-rank chunk is
            # a codec frame
            chunk = nbytes // n
            total = n * (n - 1) * _quant_wire_bytes(chunk)
            if not self.multi:
                return {"intra": int(total), "inter": 0}
            return {"intra": 0, "inter": int(total)}
        if algo == "alltoall":
            # flat exact pairwise exchange: (n-1) off-rank chunks out of
            # every rank
            total = n * (n - 1) * (nbytes // n)
            if not self.multi:
                return {"intra": int(total), "inter": 0}
            return {"intra": 0, "inter": int(total)}
        total = 2 * (n - 1) * nbytes  # ring-style total wire bytes
        if not self.multi:
            return {"intra": int(total), "inter": 0}
        return {"intra": 0, "inter": int(total)}

    def describe(self) -> dict:
        """Diag/bench-friendly summary."""
        return {
            "size": self.size,
            "n_islands": self.n_islands,
            "islands": [list(m) for m in self.islands],
            "leaders": list(self.leaders),
            "tiers": list(self.tiers),
            "fingerprint": self.fingerprint(),
        }

    def render(self) -> str:
        """One-line island map, e.g.
        ``island0[r0 r1 (shm)] | island1[r2 r3 (shm)] inter=tcp``."""
        parts = []
        for i, members in enumerate(self.islands):
            tier = min((self.tiers[r] for r in members), key=TIERS.index)
            ranks = " ".join(f"r{r}" for r in members)
            parts.append(f"island{i}[{ranks} ({tier})]")
        joined = " | ".join(parts)
        return joined + (" inter=tcp" if self.multi else " (single island)")

    def __repr__(self):
        return (f"Topology(size={self.size}, islands="
                f"{[len(m) for m in self.islands]}, "
                f"fingerprint={self.fingerprint()})")


def build_topology(fingerprints: Sequence[dict]) -> Topology:
    """Group allgathered host fingerprints into a :class:`Topology`."""
    return Topology(fingerprints)


#: live Topology per native comm handle (the bridge registers at
#: discovery, forgets at finalize/rebuild); WorldComm.topology() reads it
_by_handle: Dict[int, Topology] = {}


def get_topology(handle) -> Optional[Topology]:
    """The discovered topology of a live comm handle, or None (flat /
    discovery off / pre-topology native library)."""
    return _by_handle.get(int(handle)) if handle is not None else None


def _register(handle, topology: Topology) -> None:
    _by_handle[int(handle)] = topology


def _forget(handle) -> None:
    _by_handle.pop(int(handle), None)


def discover(handle, rank: int, size: int) -> Topology:
    """Run the bootstrap fingerprint allgather over a live comm and
    build the topology.  COLLECTIVE: every rank must call at the same
    program position (``bridge.comm_init`` does, for every rank)."""
    from ._discover import discover as _impl

    return _impl(handle, rank, size)


def __getattr__(name):
    # lazy numpy-needing re-exports, keeping the package stdlib-importable
    if name in ("simulate_hring_sum", "simulate_htree_sum",
                "simulate_ring_sum", "simulate_rd_sum",
                "simulate_ici_q_sum", "simulate_qalltoall",
                "simulate_halltoall", "simulate_hqalltoall"):
        from . import _simulate

        return getattr(_simulate, name)
    if name in ("ici_leg_active", "ici_leg_backend", "ici_leg_status"):
        from . import _ici_leg

        return getattr(_ici_leg, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
