"""The discovery handshake: allgather host fingerprints over a fresh
world comm (two collectives: fixed-width length row, then padded JSON
blobs — allgather needs equal shapes) and build the Topology.

Runs inside ``bridge.comm_init`` BEFORE the tune-table install and the
obs clock handshake, so the decision table can be keyed on the
discovered fingerprint.  Uses only numpy + the bridge (no jax): the
handshake must work for bridge-level programs and on containers where
the package's jax gate blocks the op layer."""

from __future__ import annotations

import json

import numpy as np

from . import FINGERPRINT_VERSION, Topology, local_fingerprint


def discover(handle, rank: int, size: int) -> Topology:
    from ..runtime import bridge

    fp = local_fingerprint(rank, size)
    blob = json.dumps(fp, sort_keys=True).encode()
    lens = bridge.allgather(
        handle, np.array([len(blob)], np.int64), size).ravel()
    width = int(lens.max())
    mine = np.zeros(width, np.uint8)
    mine[: len(blob)] = np.frombuffer(blob, np.uint8)
    rows = bridge.allgather(handle, mine, size)
    fingerprints = []
    for r in range(size):
        raw = bytes(rows[r][: int(lens[r])])
        try:
            parsed = json.loads(raw.decode())
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise RuntimeError(
                f"topology discovery: rank {r}'s fingerprint is "
                f"unparseable ({e}); mixed framework versions?") from e
        if int(parsed.get("v", -1)) != FINGERPRINT_VERSION:
            raise RuntimeError(
                f"topology discovery: rank {r} speaks fingerprint "
                f"version {parsed.get('v')!r}, this rank "
                f"{FINGERPRINT_VERSION} — mixed framework versions")
        fingerprints.append(parsed)
    return Topology(fingerprints)
