"""Numpy schedule simulators for the hierarchical collectives.

These replay the EXACT arithmetic the native schedules perform — the
same association order, in f32 — so tests can bit-compare the native
transport against an independent model (the same contract
``ops/quantized.py``'s ``simulate_qring_sum`` established for the
quantized schedules):

- intra-island reduce: sequential member-order folding (both native
  intra paths — the shm arena's ``vertical_reduce`` and the serial TCP
  reduce — combine in member order, so ONE simulator covers shm on and
  off); under the ICI data-plane leg (``MPI4JAX_TPU_ICI_LEG``, see
  ``topo/_ici_leg.py``) the intra phase is instead a chunked ring
  reduce-scatter/allgather per island — ``intra="ring"`` replays that
  association with the same ``simulate_ring_sum`` fold;
- ``hring`` leader leg: the chunked ring reduce-scatter/allgather
  (every chunk accumulates contributions in ring arrival order);
- ``htree`` leader leg: recursive doubling with the standard
  non-power-of-two fold (pairwise exchange; IEEE f32 addition is
  commutative, so both sides of a pair hold identical bits).

SUM only: MAX/MIN and integer reductions are association-free, so the
native result is bit-identical to the flat schedules and needs no
simulator.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np


def _chunk_lo(count: int, size: int, i: int) -> int:
    per = (count + size - 1) // size
    return min(per * i, count)


def _f32(a) -> np.ndarray:
    return np.asarray(a, np.float32)


def simulate_ring_sum(inputs: Sequence[np.ndarray]) -> np.ndarray:
    """The chunked-ring allreduce's f32 SUM association
    (native ``ring_allreduce``): reduce-scatter accumulates chunk
    contributions in ring arrival order, the allgather copies bytes —
    every rank finishes with identical bits, returned once."""
    n = len(inputs)
    if n == 1:
        return _f32(inputs[0]).copy()
    bufs = [_f32(v).copy() for v in inputs]
    count = bufs[0].size
    for step in range(n - 1):
        # every rank sends BEFORE it receives within a step: snapshot
        # the outgoing chunks, then fold
        outgoing = []
        for r in range(n):
            sc = (r - step) % n
            lo, hi = _chunk_lo(count, n, sc), _chunk_lo(count, n, sc + 1)
            outgoing.append((r, sc, bufs[r][lo:hi].copy()))
        for r, sc, data in outgoing:
            dst = (r + 1) % n
            rc = (dst - step - 1) % n
            assert rc == sc
            lo, hi = _chunk_lo(count, n, sc), _chunk_lo(count, n, sc + 1)
            bufs[dst][lo:hi] = (bufs[dst][lo:hi] + data).astype(np.float32)
    out = np.empty_like(bufs[0])
    for c in range(n):
        # after n-1 steps rank r's chunk (r+1)%n holds the full
        # reduction (the native comment's invariant), so chunk c is
        # complete at rank (c-1)%n — the allgather copies those bytes
        owner = (c - 1) % n
        lo, hi = _chunk_lo(count, n, c), _chunk_lo(count, n, c + 1)
        out[lo:hi] = bufs[owner][lo:hi]
    return out


def simulate_rd_sum(inputs: Sequence[np.ndarray]) -> np.ndarray:
    """Recursive doubling's f32 SUM association (native
    ``rd_allreduce``), including the non-power-of-two fold."""
    n = len(inputs)
    if n == 1:
        return _f32(inputs[0]).copy()
    bufs = [_f32(v).copy() for v in inputs]
    pof2 = 1
    while pof2 * 2 <= n:
        pof2 *= 2
    rem = n - pof2
    participants = {}  # newrank -> rank
    for r in range(n):
        if r < 2 * rem:
            if r % 2 == 1:
                # odd member folds the even neighbor: acc_odd += even
                bufs[r] = (bufs[r] + bufs[r - 1]).astype(np.float32)
                participants[r // 2] = r
        else:
            participants[r - rem] = r
    mask = 1
    while mask < pof2:
        snapshot = {nr: bufs[pr].copy() for nr, pr in participants.items()}
        for nr, pr in participants.items():
            bufs[pr] = (bufs[pr] + snapshot[nr ^ mask]).astype(np.float32)
        mask <<= 1
    for r in range(2 * rem):
        if r % 2 == 0:
            bufs[r] = bufs[r + 1].copy()
    return bufs[0]


def _island_sums(inputs: Sequence[np.ndarray],
                 islands: Sequence[Sequence[int]]) -> List[np.ndarray]:
    """Phase 1: sequential member-order f32 fold per island (the
    association both native intra paths share)."""
    sums = []
    for members in islands:
        acc = _f32(inputs[members[0]]).copy()
        for m in members[1:]:
            acc = (acc + _f32(inputs[m])).astype(np.float32)
        sums.append(acc)
    return sums


def _intra_sums(inputs: Sequence[np.ndarray],
                islands: Sequence[Sequence[int]],
                intra: str) -> List[np.ndarray]:
    if intra == "member":
        return _island_sums(inputs, islands)
    if intra == "ring":
        # the ICI leg's intra phase: a chunked ring reduce-scatter +
        # allgather inside each island (the Pallas kernel and its numpy
        # twin both realize exactly this fold; every member finishes
        # with identical bits, so one array per island suffices)
        return [simulate_ring_sum([inputs[m] for m in members])
                for members in islands]
    raise ValueError(f"unknown intra association {intra!r} "
                     "(expected 'member' or 'ring')")


def simulate_hring_sum(inputs: Sequence[np.ndarray],
                       islands: Sequence[Sequence[int]],
                       intra: str = "member") -> np.ndarray:
    """Bit-exact model of the native ``hring`` f32 SUM allreduce:
    ``inputs`` is one array per world rank, ``islands`` the member-rank
    lists in island order (``Topology.islands``).  Returns the result
    every rank holds (phase 3 broadcasts the leader's bytes verbatim,
    so all ranks are identical).

    ``intra`` selects the phase-1 association: ``"member"`` (native
    shm/TCP sequential fold, the default) or ``"ring"`` (the ICI
    data-plane leg's per-island ring reduce-scatter/allgather)."""
    sums = _intra_sums(inputs, islands, intra)
    return simulate_ring_sum(sums)


def simulate_htree_sum(inputs: Sequence[np.ndarray],
                       islands: Sequence[Sequence[int]],
                       intra: str = "member") -> np.ndarray:
    """Bit-exact model of the native ``htree`` f32 SUM allreduce
    (recursive-doubling leader leg).  ``intra`` as in
    :func:`simulate_hring_sum`."""
    sums = _intra_sums(inputs, islands, intra)
    return simulate_rd_sum(sums)


def _quant_refs():
    """The numpy wire-codec references from ``ops/quantized.py``.

    Package import first; standalone file load as the fallback so the
    bridge-level world programs (parent-package shim, no jax) can
    simulate the quantized ICI leg in any container."""
    global _QUANT_REFS
    if _QUANT_REFS is None:
        try:
            from ..ops import quantized as q
        except Exception:
            import importlib.util
            import os
            path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                os.pardir, "ops", "quantized.py")
            spec = importlib.util.spec_from_file_location(
                "_m4j_quantized_for_simulate", path)
            q = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(q)
        _QUANT_REFS = q
    return _QUANT_REFS


_QUANT_REFS = None


def simulate_ici_q_sum(inputs: Sequence[np.ndarray],
                       islands: Sequence[Sequence[int]]) -> np.ndarray:
    """Bit-exact model of the quantized ICI-leg f32 SUM allreduce
    (``hring+q``/``htree+q`` with ``MPI4JAX_TPU_ICI_LEG`` active).

    Phase 1 is the per-island ring fold; each island's sum is then
    packed once with the int8 wire codec (``quant_pack_ref`` — the
    in-kernel Pallas codec is bit-compatible by contract), the leaders
    exchange the packed frames losslessly, and EVERY leader dequantizes
    and folds them in island order in f32.  One qdq per contribution —
    the leader exchange itself adds no further quantization error —
    and the fold order is island order on every rank, so the result is
    rank-consistent by construction."""
    q = _quant_refs()
    sums = _intra_sums(inputs, islands, "ring")
    acc = None
    for s in sums:
        scales, codes = q.quant_pack_ref(s)
        d = q.quant_unpack_ref(scales, codes)
        acc = d if acc is None else (acc + d).astype(np.float32)
    return acc


def _qdq(q, chunk: np.ndarray) -> np.ndarray:
    scales, codes = q.quant_pack_ref(chunk.reshape(-1))
    return q.quant_unpack_ref(scales, codes).reshape(chunk.shape)


def simulate_qalltoall(inputs: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Bit-exact model of the native ``qalltoall`` f32 exchange:
    ``inputs`` is one ``(size, count...)`` array per world rank; returns
    each rank's output.  Every off-rank chunk rides the int8+scales wire
    codec — the destination dequantizes the SENDER's packed bytes, so
    rank consistency is by construction — while the own-rank chunk is a
    local copy and stays exact.  bf16 callers model the native staging
    by upcasting to f32 before and RNE-rounding after (the codec itself
    always runs in f32)."""
    q = _quant_refs()
    n = len(inputs)
    outs = []
    for dst in range(n):
        chunks = []
        for src in range(n):
            c = _f32(inputs[src][dst])
            chunks.append(c.copy() if src == dst else _qdq(q, c))
        outs.append(np.stack(chunks))
    return outs


def simulate_halltoall(inputs: Sequence[np.ndarray]) -> List[np.ndarray]:
    """Model of the exact hierarchical alltoall: ``halltoall`` is a pure
    permutation (every leg moves bytes verbatim), so its output is
    bit-identical to the flat pairwise exchange regardless of the island
    partition — which is exactly what this returns."""
    n = len(inputs)
    return [np.stack([_f32(inputs[src][dst]) for src in range(n)])
            for dst in range(n)]


def simulate_hqalltoall(inputs: Sequence[np.ndarray],
                        islands: Sequence[Sequence[int]]
                        ) -> List[np.ndarray]:
    """Bit-exact model of ``hqalltoall``: intra-island chunks (own chunk
    included) are exact; each cross-island block — all chunks from
    island ``a`` to island ``b``, concatenated src-member-major in
    member order — is quantized as ONE codec frame on the leader leg,
    so the 256-element codec blocks span chunk boundaries exactly as
    the native leader exchange packs them."""
    q = _quant_refs()
    n = len(inputs)
    chunk_shape = _f32(inputs[0][0]).shape
    count = int(np.prod(chunk_shape, dtype=np.int64)) if chunk_shape else 1
    outs = [np.empty((n,) + chunk_shape, np.float32) for _ in range(n)]
    for a, mem_a in enumerate(islands):
        for b, mem_b in enumerate(islands):
            if a == b:
                for s in mem_a:
                    for t in mem_b:
                        outs[t][s] = _f32(inputs[s][t])
                continue
            block = np.concatenate([_f32(inputs[s][t]).reshape(-1)
                                    for s in mem_a for t in mem_b])
            d = _qdq(q, block)
            i = 0
            for s in mem_a:
                for t in mem_b:
                    outs[t][s] = d[i * count:(i + 1) * count].reshape(
                        chunk_shape)
                    i += 1
    return outs
