"""Pipeline-parallel GPT: layers split into stages along a mesh axis.

BASELINE.md's "Pipeline-parallel GPT-2 124M via point-to-point" config.
The reference realizes pipelines as token-ordered send/recv chains between
rank processes (SURVEY.md §2.4); here the schedule is the SPMD GPipe of
``parallel/pipeline.py`` — one ``ppermute`` handoff per tick, microbatches
filling the bubble, reverse-mode autodiff replaying the schedule backward.

Layout: each stage owns ``n_layers/pp`` transformer blocks (params carry a
leading ``pp`` axis, sharded over the mesh); embeddings are replicated
(stage 0 embeds via the pipeline's ``prepare_fn``, the last stage applies
the final norm + tied unembedding).  Compose with dp by adding a mesh axis
and sharding the batch.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import ops
from ..parallel.mesh import MeshComm
from ..parallel.pipeline import pipeline_apply
from .transformer import GPTConfig, _layernorm


class PPGPTParams(NamedTuple):
    wte: jax.Array   # (vocab, d)      replicated
    wpe: jax.Array   # (max_seq, d)    replicated
    lnf: jax.Array   # (2, d)          replicated
    # stage-sharded stacks: leading pp axis, then layers-per-stage
    ln1: jax.Array   # (pp, Ls, 2, d)
    ln2: jax.Array   # (pp, Ls, 2, d)
    w_qkv: jax.Array  # (pp, Ls, d, 3d)
    w_o: jax.Array    # (pp, Ls, d, d)
    w1: jax.Array     # (pp, Ls, d, ff)
    b1: jax.Array     # (pp, Ls, ff)
    w2: jax.Array     # (pp, Ls, ff, d)
    b2: jax.Array     # (pp, Ls, d)


REPLICATED = ("wte", "wpe", "lnf")


def init_params(cfg: GPTConfig, pp: int, seed: int = 0) -> PPGPTParams:
    if cfg.n_layers % pp:
        raise ValueError(f"n_layers ({cfg.n_layers}) must divide pp ({pp})")
    ls = cfg.n_layers // pp
    d, ff = cfg.d_model, cfg.d_ff
    rng = np.random.RandomState(seed)
    s = 0.02

    def norm(*shape):
        return jnp.asarray(rng.randn(*shape).astype(np.float32) * s)

    ln = jnp.stack(
        [jnp.ones((pp, ls, d), jnp.float32),
         jnp.zeros((pp, ls, d), jnp.float32)], axis=2,
    )
    return PPGPTParams(
        wte=norm(cfg.vocab, d),
        wpe=norm(cfg.max_seq, d),
        lnf=jnp.stack(
            [jnp.ones((d,), jnp.float32), jnp.zeros((d,), jnp.float32)]
        ),
        ln1=ln, ln2=ln,
        w_qkv=norm(pp, ls, d, 3 * d),
        w_o=norm(pp, ls, d, d),
        w1=norm(pp, ls, d, ff),
        b1=jnp.zeros((pp, ls, ff), jnp.float32),
        w2=norm(pp, ls, ff, d),
        b2=jnp.zeros((pp, ls, d), jnp.float32),
    )


def param_specs(pp_axis: str = "pp") -> PPGPTParams:
    return PPGPTParams(
        **{f: P() for f in REPLICATED},
        **{
            f: P(pp_axis)
            for f in PPGPTParams._fields
            if f not in REPLICATED
        },
    )


def _causal_attention(x, w_qkv, w_o, n_heads):
    b, t, d = x.shape
    hd = d // n_heads
    qkv = (x @ w_qkv).reshape(b, t, 3, n_heads, hd)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    mask = jnp.tril(jnp.ones((t, t), bool))
    scores = jnp.where(mask[None, None], scores, jnp.finfo(scores.dtype).min)
    p = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", p, v).reshape(b, t, d)
    return out @ w_o


class PPGPT:
    def __init__(self, cfg: GPTConfig, mesh: Mesh, pp_axis: str = "pp"):
        self.cfg = cfg
        self.mesh = mesh
        self.pp_axis = pp_axis
        self.pp = mesh.shape[pp_axis]

    def _stage(self, stage_params, x):
        """Run this stage's block stack on activations (B, T, d)."""
        cfg = self.cfg
        ln1, ln2, w_qkv, w_o, w1, b1, w2, b2 = stage_params

        def block(x_, layer):
            l1, l2, wq, wo, a1, c1, a2, c2 = layer
            y = _causal_attention(
                _layernorm(x_, l1), wq, wo, cfg.n_heads
            )
            x_ = x_ + y
            h = jax.nn.gelu(_layernorm(x_, l2) @ a1 + c1)
            return x_ + (h @ a2 + c2), None

        x, _ = lax.scan(block, x, (ln1, ln2, w_qkv, w_o, w1, b1, w2, b2))
        return x

    def loss_fn(self):
        """Per-rank pipelined loss: ``loss(params, tokens, targets, mask)``
        with tokens (M, B_mb, T) microbatched; call inside shard_map."""
        cfg = self.cfg

        def loss(params: PPGPTParams, tokens, targets, mask):
            idx = lax.axis_index(self.pp_axis)
            is_last = idx == self.pp - 1
            stage = tuple(
                getattr(params, f)[0]
                for f in ("ln1", "ln2", "w_qkv", "w_o", "w1", "b1", "w2",
                          "b2")
            )

            def prepare(mb_tokens):
                t = mb_tokens.shape[-1]
                return (
                    params.wte[mb_tokens]
                    + params.wpe[:t][None]
                )

            acts = pipeline_apply(
                self._stage, stage, tokens, axis=self.pp_axis,
                prepare_fn=prepare,
            )  # (M, B_mb, T, d); zeros except on the last stage

            x = _layernorm(acts, params.lnf)
            logits = x @ params.wte.T
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
            nll = -jnp.take_along_axis(
                logp, targets[..., None], axis=-1
            )[..., 0]
            local = jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
            # only the last stage's numbers are real; share them
            contrib = jnp.where(is_last, local, 0.0)
            return ops.allreduce(
                contrib, op=ops.SUM, comm=MeshComm(self.pp_axis,
                                                   mesh=self.mesh)
            )

        return loss

    def train_step_fn(self, lr: float = 3e-4):
        """SGD step: ``step(params, tokens) -> (loss, params)``; tokens
        (M, B_mb, T) int32 microbatches, replicated."""
        specs = param_specs(self.pp_axis)
        loss_fn = self.loss_fn()

        def per_rank(params, tokens, targets, mask):
            loss, grads = jax.value_and_grad(loss_fn)(
                params, tokens, targets, mask
            )
            # stage-sharded grads are local; replicated params (embeddings,
            # final norm) accumulate contributions from every stage
            ppc = MeshComm(self.pp_axis, mesh=self.mesh)

            def sync(f, g):
                if f in REPLICATED:
                    return ops.allreduce(g, op=ops.SUM, comm=ppc)
                return g

            grads = PPGPTParams(
                **{f: sync(f, getattr(grads, f))
                   for f in PPGPTParams._fields}
            )
            params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
            return loss[None], params

        mapped = jax.shard_map(
            per_rank,
            mesh=self.mesh,
            in_specs=(specs, P(), P(), P()),
            out_specs=(P(self.pp_axis), specs),
            check_vma=False,
        )

        @jax.jit
        def step(params, tokens):
            targets = jnp.concatenate(
                [tokens[..., 1:], jnp.zeros_like(tokens[..., :1])], axis=-1
            )
            mask = jnp.concatenate(
                [
                    jnp.ones(tokens[..., 1:].shape, jnp.float32),
                    jnp.zeros(tokens[..., :1].shape, jnp.float32),
                ],
                axis=-1,
            )
            loss, params2 = mapped(params, tokens, targets, mask)
            return loss[0], params2

        return step
