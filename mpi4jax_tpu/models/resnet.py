"""Data-parallel residual CNN (the grad-allreduce training config).

BASELINE.md lists "Data-parallel ResNet-50 grad allreduce" among the
reference's benchmark configs; the reference itself only provides the
collective (differentiable allreduce).  This module supplies the model
family: a parameterizable residual CNN (depth/width scale up to
ResNet-50-class) trained data-parallel with the framework's
allreduce-synced gradients (parallel/dp.py).

TPU notes: convolutions run through ``lax.conv_general_dilated`` in NHWC
(MXU-friendly); normalization is GroupNorm (stateless — no cross-device
batch statistics, so DP sync is gradients-only).
"""

from __future__ import annotations

from typing import NamedTuple, Sequence

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from ..parallel import dp


class ResNetConfig(NamedTuple):
    stages: Sequence[int] = (2, 2, 2, 2)   # blocks per stage (ResNet-18)
    widths: Sequence[int] = (64, 128, 256, 512)
    n_classes: int = 10
    in_channels: int = 3
    groups: int = 8
    block: str = "basic"  # "basic": two 3x3 convs (ResNet-18/34);
    #                       "bottleneck": 1x1 -> 3x3 -> 1x1 with 4x
    #                       expansion (ResNet-50-class: stages
    #                       (3, 4, 6, 3) + bottleneck = ResNet-50)
    dtype: str = "float32"  # conv compute dtype; "bfloat16" on real TPU
    # mixed precision: master params stay f32 (the optimizer update and
    # the DP grad-allreduce run in f32); forward casts per use, autodiff
    # transposes the casts so grads come back f32.  Measured r3 on the
    # v5e at ResNet-34/B=32/224^2: 6.5x over f32 convs (f32 hits the
    # MXU at 1/8 rate).
    stem: str = "small"  # "small": 3x3/1 conv, no pool (CIFAR-style,
    #                      the historical default — keeps existing
    #                      configs/params valid); "imagenet": 7x7/2
    #                      conv + 3x3/2 avg pool, the standard ResNet
    #                      head — stage 1 sees 1/16 the pixels (use
    #                      for 224^2-class inputs)


def _expansion(cfg: ResNetConfig) -> int:
    return 4 if cfg.block == "bottleneck" else 1


def resnet50_config(**overrides) -> ResNetConfig:
    """The BASELINE.md-named config: ResNet-50 = bottleneck (3, 4, 6, 3)."""
    base = dict(stages=(3, 4, 6, 3), block="bottleneck", n_classes=1000,
                stem="imagenet")
    base.update(overrides)
    return ResNetConfig(**base)


def _conv(x, w, stride=1):
    return lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def _groupnorm(x, scale, bias, groups):
    # normalization statistics in f32 regardless of the compute dtype
    # (bf16 mean/var over 224^2 spatial positions loses too many bits)
    dt = x.dtype
    n, h, w, c = x.shape
    g = min(groups, c)
    xg = x.astype(jnp.float32).reshape(n, h, w, g, c // g)
    mu = jnp.mean(xg, axis=(1, 2, 4), keepdims=True)
    var = jnp.var(xg, axis=(1, 2, 4), keepdims=True)
    xg = (xg - mu) * lax.rsqrt(var + 1e-5)
    return (xg.reshape(n, h, w, c) * scale + bias).astype(dt)


def init_params(cfg: ResNetConfig, seed: int = 0):
    rng = np.random.RandomState(seed)

    def conv_w(k, cin, cout):
        fan = k * k * cin
        return jnp.asarray(
            (rng.randn(k, k, cin, cout) * np.sqrt(2.0 / fan)).astype(
                np.float32
            )
        )

    exp = _expansion(cfg)
    stem_k = 7 if cfg.stem == "imagenet" else 3
    params = {
        "stem": conv_w(stem_k, cfg.in_channels, cfg.widths[0]),
        "stem_gn": (jnp.ones(cfg.widths[0]), jnp.zeros(cfg.widths[0])),
        "stages": [],
        "head": jnp.asarray(
            (rng.randn(cfg.widths[-1] * exp, cfg.n_classes) * 0.01).astype(
                np.float32
            )
        ),
        "head_b": jnp.zeros(cfg.n_classes),
    }
    cin = cfg.widths[0]
    for si, (depth, width) in enumerate(zip(cfg.stages, cfg.widths)):
        blocks = []
        for b in range(depth):
            stride, has_proj = _block_plan(cfg, si, b, cin)
            del stride  # static; recomputed in forward
            cout = width * exp
            if cfg.block == "bottleneck":
                blk = {
                    "conv1": conv_w(1, cin, width),
                    "gn1": (jnp.ones(width), jnp.zeros(width)),
                    "conv2": conv_w(3, width, width),
                    "gn2": (jnp.ones(width), jnp.zeros(width)),
                    "conv3": conv_w(1, width, cout),
                    "gn3": (jnp.ones(cout), jnp.zeros(cout)),
                    "proj": conv_w(1, cin, cout) if has_proj else None,
                }
            else:
                blk = {
                    "conv1": conv_w(3, cin, width),
                    "gn1": (jnp.ones(width), jnp.zeros(width)),
                    "conv2": conv_w(3, width, width),
                    "gn2": (jnp.ones(width), jnp.zeros(width)),
                    "proj": conv_w(1, cin, cout) if has_proj else None,
                }
            blocks.append(blk)
            cin = cout
        params["stages"].append(blocks)
    return params


def _block_plan(cfg: ResNetConfig, stage: int, block: int, cin: int):
    """Static (stride, needs_projection) for a block — shared by init and
    forward so the pytree holds arrays only."""
    width = cfg.widths[stage] * _expansion(cfg)
    stride = 2 if (block == 0 and stage > 0) else 1
    return stride, (cin != width or stride > 1)


def forward(params, x, cfg: ResNetConfig):
    g = cfg.groups
    x = x.astype(jnp.dtype(cfg.dtype))
    stem_stride = 2 if cfg.stem == "imagenet" else 1
    h = jnp.maximum(
        _groupnorm(
            _conv(x, params["stem"], stem_stride), *params["stem_gn"], g
        ),
        0,
    )
    if cfg.stem == "imagenet":
        # 3x3/2 average pool as a depthwise conv (constant 1/9 kernel):
        # fully differentiable and MXU-scheduled.  Max pool's
        # SelectAndScatter gradient hangs the tunnel's remote compile
        # helper at this size (and is slower on TPU generally).
        c = h.shape[-1]
        kern = jnp.full((3, 3, 1, c), 1.0 / 9.0, h.dtype)
        h = lax.conv_general_dilated(
            h, kern, (2, 2), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=c,
        )
    cin = cfg.widths[0]
    exp = _expansion(cfg)
    for si, blocks in enumerate(params["stages"]):
        for b, blk in enumerate(blocks):
            stride, _ = _block_plan(cfg, si, b, cin)
            if cfg.block == "bottleneck":
                # 1x1 reduce -> 3x3 (strided) -> 1x1 expand
                y = jnp.maximum(
                    _groupnorm(_conv(h, blk["conv1"]), *blk["gn1"], g), 0
                )
                y = jnp.maximum(
                    _groupnorm(
                        _conv(y, blk["conv2"], stride), *blk["gn2"], g
                    ),
                    0,
                )
                y = _groupnorm(_conv(y, blk["conv3"]), *blk["gn3"], g)
            else:
                y = _conv(h, blk["conv1"], stride)
                y = jnp.maximum(_groupnorm(y, *blk["gn1"], g), 0)
                y = _groupnorm(_conv(y, blk["conv2"]), *blk["gn2"], g)
            skip = h
            if blk["proj"] is not None:
                skip = _conv(h, blk["proj"], stride)
            h = jnp.maximum(y + skip, 0)
            cin = cfg.widths[si] * exp
    pooled = jnp.mean(h.astype(jnp.float32), axis=(1, 2))
    return pooled @ params["head"] + params["head_b"]


def make_dp_train_step(cfg: ResNetConfig, mesh, lr=1e-2, axis="mpi"):
    """Jitted DP training step: batch sharded over ``axis``, grads synced
    with allreduce-mean."""
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import MeshComm

    comm = MeshComm(axis, mesh=mesh)

    def local_loss(params, xb, yb):
        logits = forward(params, xb, cfg)
        logp = jax.nn.log_softmax(logits)
        return -jnp.mean(
            jnp.take_along_axis(logp, yb[:, None], axis=-1)
        )

    def per_rank(params, xb, yb):
        loss, grads = dp.value_and_synced_grad(local_loss, comm=comm)(
            params, xb, yb
        )
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        return loss[None], params

    # `stride`/None leaves are static pytree data; strip them from specs
    def spec_tree(tree):
        return jax.tree.map(lambda _: P(), tree)

    example = init_params(cfg)

    mapped = jax.shard_map(
        per_rank,
        mesh=mesh,
        in_specs=(spec_tree(example), P(axis), P(axis)),
        out_specs=(P(axis), spec_tree(example)),
        check_vma=False,
    )

    @jax.jit
    def step(params, images, labels):
        loss, params = mapped(params, images, labels)
        return loss[0], params

    return step
