"""Fused Pallas step kernel for the single-block shallow-water solver.

The XLA step (`shallow_water._step_local`) materializes ~a dozen
intermediate fields per step (fe/fn/q/ke, viscous gradients, pads, ghost
updates) — on one chip each is a full HBM round-trip, and the step is
bandwidth-bound.  This kernel computes the ENTIRE step — flux/vorticity
build, Adams–Bashforth update, wall + periodic-wrap boundary handling,
and the viscous pass — inside VMEM row-tiles: 6 field reads + 6 field
writes of HBM traffic per step, nothing else.

Scope: single-block grids (1×1 ``ProcessGrid``) with ``periodic_x=True``
— exactly the dense per-chip core.  Decomposed grids keep the XLA path,
where the halo exchanges between sub-steps are the multi-chip collectives
(the kernel's row-window trick cannot see a neighbor *rank*'s rows).

Numerical contract: identical stencils to ``_step_local`` (same Sadourny
C-grid expressions, same boundary-mask ordering as ``_exchange``'s
kinds), so results match the XLA path to f32 reassociation tolerance —
asserted by ``tests/models/test_sw_pallas.py``.

Window discipline: each grid step processes ``T`` output rows from an
``R = T + 8``-row input window (clamped at the domain edges).  Every
derived level consumes one neighbor row, and the chain
fe/fn/q/ke → d*_new → AB state → viscous gradients → final state is four
levels deep on each side.  Rows that fall outside the domain are repaired
by the ghost-row masks (walls in y), so windows touching the domain edge
stay valid all the way out.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

HALO_ROWS = 4  # stencil-chain depth per side


def _interpret(flag):
    if flag is None:
        from ..ops.flash import target_platform

        flag = target_platform() != "tpu"
    return pltpu.InterpretParams() if flag else False


# window shift helpers: value at (r, c) of the result reads the neighbor
# of (r, c) in the argument; window-edge garbage is absorbed by the halo
# rows / rebuilt ghost columns.
def _ex(a):  # east: col + 1
    return jnp.concatenate([a[:, 1:], a[:, -1:]], axis=1)


def _wx(a):  # west: col - 1
    return jnp.concatenate([a[:, :1], a[:, :-1]], axis=1)


def _nx(a):  # north: row + 1
    return jnp.concatenate([a[1:], a[-1:]], axis=0)


def _sx(a):  # south: row - 1
    return jnp.concatenate([a[:1], a[:-1]], axis=0)


def _make_step_kernel(*, nyp, X, T, R, dx, dy, g, nu, dt, f0, beta,
                      ab_a, ab_b):
    nx = X - 2

    def wrapc(a):
        # periodic-x ghost columns from the interior columns (full height,
        # matching the exchange's full-column wrap strips)
        return jnp.concatenate(
            [a[:, nx:nx + 1], a[:, 1:X - 1], a[:, 1:2]], axis=1
        )

    def kernel(h_hbm, u_hbm, v_hbm, dh_hbm, du_hbm, dv_hbm,
               ho_hbm, uo_hbm, vo_hbm, dho_hbm, duo_hbm, dvo_hbm,
               hw, uw, vw, dhw, duw, dvw,
               in_sems, out_sems):
        i = pl.program_id(0)
        in_start = jnp.clip(i * T - HALO_ROWS, 0, nyp - R)
        out_start = jnp.minimum(i * T, nyp - T)

        loads = [
            pltpu.make_async_copy(
                src.at[pl.ds(in_start, R)], dst, in_sems.at[j]
            )
            for j, (src, dst) in enumerate(
                [(h_hbm, hw), (u_hbm, uw), (v_hbm, vw),
                 (dh_hbm, dhw), (du_hbm, duw), (dv_hbm, dvw)]
            )
        ]
        for c in loads:
            c.start()
        for c in loads:
            c.wait()

        h = hw[...]
        u = uw[...]
        v = vw[...]
        dh = dhw[...]
        du = duw[...]
        dv = dvw[...]

        gidx = in_start + lax.broadcasted_iota(jnp.int32, (R, X), 0)
        ghost_row = (gidx == 0) | (gidx == nyp - 1)
        col = lax.broadcasted_iota(jnp.int32, (R, X), 1)
        interior = (~ghost_row) & (col >= 1) & (col <= nx)

        def pad_mask(a):
            # _pad semantics: ghost ring zero (x-ghosts rebuilt by wrapc)
            return wrapc(jnp.where(ghost_row, 0.0, a))

        # hc: h's interior with edge-copied ghost rows (jnp.pad mode="edge")
        hc = jnp.where(gidx == 0, _nx(h), h)
        hc = jnp.where(gidx == nyp - 1, _sx(hc), hc)
        hc = wrapc(hc)

        # flux / vorticity / kinetic-energy fields (interior expressions;
        # ghosts = _pad zeros + exchange: x-wrap, fn gets the v-point wall)
        fe = pad_mask(0.5 * (hc + _ex(hc)) * u)
        fn = pad_mask(0.5 * (hc + _nx(hc)) * v)
        fn = jnp.where(gidx == nyp - 2, 0.0, fn)  # kind "v" wall mask
        y = (gidx - 1).astype(jnp.float32) * dy
        f = f0 + beta * y
        zeta = (_ex(v) - v) / dx - (_nx(u) - u) / dy
        thick = 0.25 * (hc + _ex(hc) + _nx(hc) + _nx(_ex(hc)))
        q = pad_mask((f + zeta) / thick)
        ke = pad_mask(0.5 * (0.5 * (u ** 2 + _wx(u) ** 2)
                             + 0.5 * (v ** 2 + _sx(v) ** 2)))

        # tendencies (valid on interior rows ≥ 2 levels from window edge)
        dh_new = -(fe - _wx(fe)) / dx - (fn - _sx(fn)) / dy
        du_new = (-g * (_ex(h) - h) / dx
                  + 0.5 * (q * 0.5 * (fn + _ex(fn))
                           + _sx(q) * 0.5 * (_sx(fn) + _sx(_ex(fn))))
                  - (_ex(ke) - ke) / dx)
        dv_new = (-g * (_nx(h) - h) / dy
                  - 0.5 * (q * 0.5 * (fe + _nx(fe))
                           + _wx(q) * 0.5 * (_wx(fe) + _nx(_wx(fe))))
                  - (_nx(ke) - ke) / dy)

        # Adams–Bashforth update (interior), ghosts keep the BC values
        hn = jnp.where(interior, h + dt * (ab_a * dh_new + ab_b * dh), h)
        un = jnp.where(interior, u + dt * (ab_a * du_new + ab_b * du), u)
        vn = jnp.where(interior, v + dt * (ab_a * dv_new + ab_b * dv), v)
        hn, un, vn = wrapc(hn), wrapc(un), wrapc(vn)
        vn = jnp.where(gidx == nyp - 2, 0.0, vn)  # kind "v" wall mask

        # viscous pass (kinds "u","v","u","v": the y-gradients carry the
        # v-point wall mask, mirroring _exchange's kind list)
        gxu = pad_mask(nu * (_ex(un) - un) / dx)
        gyu = pad_mask(nu * (_nx(un) - un) / dy)
        gyu = jnp.where(gidx == nyp - 2, 0.0, gyu)
        gxv = pad_mask(nu * (_ex(vn) - vn) / dx)
        gyv = pad_mask(nu * (_nx(vn) - vn) / dy)
        gyv = jnp.where(gidx == nyp - 2, 0.0, gyv)

        uf = jnp.where(
            interior,
            un + dt * ((gxu - _wx(gxu)) / dx + (gyu - _sx(gyu)) / dy),
            un,
        )
        vf = jnp.where(
            interior,
            vn + dt * ((gxv - _wx(gxv)) / dx + (gyv - _sx(gyv)) / dy),
            vn,
        )
        uf, vf = wrapc(uf), wrapc(vf)
        vf = jnp.where(gidx == nyp - 2, 0.0, vf)

        # the input windows are fully consumed — reuse them as staging for
        # the results, then DMA the T output rows out of each (Mosaic can
        # dynamic-slice refs for DMA, not values)
        off = out_start - in_start
        hw[...] = hn
        uw[...] = uf
        vw[...] = vf
        dhw[...] = jnp.where(interior, dh_new, 0.0)
        duw[...] = jnp.where(interior, du_new, 0.0)
        dvw[...] = jnp.where(interior, dv_new, 0.0)

        stores = [
            pltpu.make_async_copy(
                src.at[pl.ds(off, T)], dst.at[pl.ds(out_start, T)],
                out_sems.at[j],
            )
            for j, (src, dst) in enumerate(
                [(hw, ho_hbm), (uw, uo_hbm), (vw, vo_hbm),
                 (dhw, dho_hbm), (duw, duo_hbm), (dvw, dvo_hbm)]
            )
        ]
        for c in stores:
            c.start()
        for c in stores:
            c.wait()

    return kernel


def fused_step(state, params, *, first: bool, interpret=None,
               tile_rows: int = 16):
    """One full shallow-water step as a single Pallas kernel.

    ``state`` fields are single-block padded arrays ``(ny+2, nx+2)`` with
    valid ghosts (the step_fn invariant).  Returns the next state with the
    same invariant.  ``first=True`` is the Euler bootstrap (AB with
    a=1, b=0, matching ``_step_local(first=True)``).
    """
    h = state[0]
    nyp, X = h.shape
    T = min(tile_rows, nyp)
    R = min(T + 2 * HALO_ROWS, nyp)
    if R < 2 * HALO_ROWS + 1 and R < nyp:  # pragma: no cover - guard
        raise ValueError("tile too small")
    p = params
    kern = _make_step_kernel(
        nyp=nyp, X=X, T=T, R=R,
        dx=p.dx, dy=p.dy, g=p.gravity, nu=p.viscosity, dt=p.dt,
        f0=p.coriolis_f, beta=p.coriolis_beta,
        ab_a=1.0 if first else p.ab_a,
        ab_b=0.0 if first else p.ab_b,
    )
    ntiles = -(-nyp // T)
    struct = jax.ShapeDtypeStruct((nyp, X), jnp.float32)
    outs = pl.pallas_call(
        kern,
        grid=(ntiles,),
        out_shape=(struct,) * 6,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 6,
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 6,
        scratch_shapes=(
            [pltpu.VMEM((R, X), jnp.float32)] * 6
            + [pltpu.SemaphoreType.DMA((6,)), pltpu.SemaphoreType.DMA((6,))]
        ),
        interpret=_interpret(interpret),
    )(*(f.astype(jnp.float32) for f in state))
    return type(state)(*outs)
