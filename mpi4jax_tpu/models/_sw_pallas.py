"""Fused Pallas step kernel for the single-block shallow-water solver.

The XLA step (`shallow_water._step_local`) materializes ~a dozen
intermediate fields per step (fe/fn/q/ke, viscous gradients, pads, ghost
updates) — on one chip each is a full HBM round-trip, and the step is
bandwidth-bound.  This kernel computes the ENTIRE step — flux/vorticity
build, Adams–Bashforth update, wall + periodic-wrap boundary handling,
and the viscous pass — inside VMEM row-tiles: 6 field reads + 6 field
writes of HBM traffic per step, nothing else.

Scope: single-block grids (1×1 ``ProcessGrid``) with ``periodic_x=True``
— exactly the dense per-chip core.  Decomposed grids keep the XLA path,
where the halo exchanges between sub-steps are the multi-chip collectives
(the kernel's row-window trick cannot see a neighbor *rank*'s rows).

Numerical contract: identical stencils to ``_step_local`` (same Sadourny
C-grid expressions, same boundary-mask ordering as ``_exchange``'s
kinds), so results match the XLA path to f32 reassociation tolerance —
asserted by ``tests/models/test_sw_pallas.py``.

Window discipline: each grid step processes ``T`` output rows from an
``R = T + 16``-row input window (clamped at the array edges).  Every
derived level consumes one neighbor row, and the chain
fe/fn/q/ke → d*_new → AB state → viscous gradients → final state is four
levels deep on each side, so 8 halo rows per side is ample.  Rows that
fall outside the domain are repaired by the ghost-row masks (walls in
y), so windows touching the domain edge stay valid all the way out.

Alignment discipline (Mosaic): HBM refs are (8, 128)-tiled, and dynamic
DMA slice starts in the row dimension must be provably divisible by 8.
Row counts are therefore padded up to a multiple of the row tile ``T``
(itself a multiple of 8) *before* the kernel — see ``pad_rows`` /
``unpad_rows`` — so that every window start ``clip(i*T - 8, 0,
nyp_pad - R)``, output start ``i*T``, and staging offset is a multiple
of 8.  The padded rows sit beyond the ``gidx >= nyp - 1`` ghost mask
and stay identically zero across steps.  (Round 1 shipped unaligned
starts ≡ 4 (mod 8) and failed Mosaic compilation on real TPUs —
VERDICT.md weak #1; this layout is the fix.)
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

HALO_ROWS = 8  # stencil chain is 4 deep per side; 8 keeps DMA starts tile-aligned


def _interpret(flag):
    if flag is None:
        from ..ops.flash import target_platform

        flag = target_platform() != "tpu"
    return pltpu.InterpretParams() if flag else False


# window shift helpers: value at (r, c) of the result reads the neighbor
# of (r, c) in the argument; window-edge garbage is absorbed by the halo
# rows / rebuilt ghost columns.
def _ex(a):  # east: col + 1
    return jnp.concatenate([a[:, 1:], a[:, -1:]], axis=1)


def _wx(a):  # west: col - 1
    return jnp.concatenate([a[:, :1], a[:, :-1]], axis=1)


def _nx(a):  # north: row + 1
    return jnp.concatenate([a[1:], a[-1:]], axis=0)


def _sx(a):  # south: row - 1
    return jnp.concatenate([a[:1], a[:-1]], axis=0)


def _make_step_kernel(*, nyp, nyp_pad, X, Xp, T, R, dx, dy, g, nu, dt,
                      f0, beta, ab_a, ab_b):
    # X is the logical block width (nx + 2 ghosts); Xp >= X is the
    # 128-aligned padded width the VMEM windows actually carry.  Columns
    # >= X are alignment padding, kept identically zero.
    nx = X - 2

    def wrapc(a):
        # periodic-x ghost columns from the interior columns (full height,
        # matching the exchange's full-column wrap strips); the padding
        # tail passes through unchanged (zeros)
        parts = [a[:, nx:nx + 1], a[:, 1:X - 1], a[:, 1:2]]
        if Xp > X:
            parts.append(a[:, X:])
        return jnp.concatenate(parts, axis=1)

    def kernel(h_hbm, u_hbm, v_hbm, dh_hbm, du_hbm, dv_hbm,
               ho_hbm, uo_hbm, vo_hbm, dho_hbm, duo_hbm, dvo_hbm,
               hw, uw, vw, dhw, duw, dvw,
               in_sems, out_sems):
        i = pl.program_id(0)
        # compute starts in units of 8-row tiles and scale up at the end:
        # Mosaic must *prove* divisibility by the (8, 128) tiling, and
        # `8 * k` is provable where `clip(...)` of runtime-multiples-of-8
        # is not (T % 8 == 0, nyp_pad % T == 0, R % 8 == 0 make the tile
        # arithmetic exact)
        in_t = jnp.clip(i * (T // 8) - HALO_ROWS // 8, 0, (nyp_pad - R) // 8)
        out_t = jnp.minimum(i * (T // 8), (nyp_pad - T) // 8)
        in_start = in_t * 8
        out_start = out_t * 8

        loads = [
            pltpu.make_async_copy(
                src.at[pl.ds(in_start, R)], dst, in_sems.at[j]
            )
            for j, (src, dst) in enumerate(
                [(h_hbm, hw), (u_hbm, uw), (v_hbm, vw),
                 (dh_hbm, dhw), (du_hbm, duw), (dv_hbm, dvw)]
            )
        ]
        for c in loads:
            c.start()
        for c in loads:
            c.wait()

        h = hw[...]
        u = uw[...]
        v = vw[...]
        dh = dhw[...]
        du = duw[...]
        dv = dvw[...]

        gidx = in_start + lax.broadcasted_iota(jnp.int32, (R, Xp), 0)
        # >= nyp - 1 (not ==) so alignment-padding rows beyond the domain
        # are masked like ghosts and stay identically zero across steps
        ghost_row = (gidx == 0) | (gidx >= nyp - 1)
        col = lax.broadcasted_iota(jnp.int32, (R, Xp), 1)
        interior = (~ghost_row) & (col >= 1) & (col <= nx)

        def pad_mask(a):
            # _pad semantics: ghost ring zero (x-ghosts rebuilt by wrapc)
            return wrapc(jnp.where(ghost_row, 0.0, a))

        # hc: h's interior with edge-copied ghost rows (jnp.pad mode="edge")
        hc = jnp.where(gidx == 0, _nx(h), h)
        hc = jnp.where(gidx == nyp - 1, _sx(hc), hc)
        hc = wrapc(hc)

        # flux / vorticity / kinetic-energy fields (interior expressions;
        # ghosts = _pad zeros + exchange: x-wrap, fn gets the v-point wall)
        fe = pad_mask(0.5 * (hc + _ex(hc)) * u)
        fn = pad_mask(0.5 * (hc + _nx(hc)) * v)
        fn = jnp.where(gidx == nyp - 2, 0.0, fn)  # kind "v" wall mask
        y = (gidx - 1).astype(jnp.float32) * dy
        f = f0 + beta * y
        zeta = (_ex(v) - v) / dx - (_nx(u) - u) / dy
        thick = 0.25 * (hc + _ex(hc) + _nx(hc) + _nx(_ex(hc)))
        q = pad_mask((f + zeta) / thick)
        ke = pad_mask(0.5 * (0.5 * (u ** 2 + _wx(u) ** 2)
                             + 0.5 * (v ** 2 + _sx(v) ** 2)))

        # tendencies (valid on interior rows ≥ 2 levels from window edge)
        dh_new = -(fe - _wx(fe)) / dx - (fn - _sx(fn)) / dy
        du_new = (-g * (_ex(h) - h) / dx
                  + 0.5 * (q * 0.5 * (fn + _ex(fn))
                           + _sx(q) * 0.5 * (_sx(fn) + _sx(_ex(fn))))
                  - (_ex(ke) - ke) / dx)
        dv_new = (-g * (_nx(h) - h) / dy
                  - 0.5 * (q * 0.5 * (fe + _nx(fe))
                           + _wx(q) * 0.5 * (_wx(fe) + _nx(_wx(fe))))
                  - (_nx(ke) - ke) / dy)

        # Adams–Bashforth update (interior), ghosts keep the BC values
        hn = jnp.where(interior, h + dt * (ab_a * dh_new + ab_b * dh), h)
        un = jnp.where(interior, u + dt * (ab_a * du_new + ab_b * du), u)
        vn = jnp.where(interior, v + dt * (ab_a * dv_new + ab_b * dv), v)
        hn, un, vn = wrapc(hn), wrapc(un), wrapc(vn)
        vn = jnp.where(gidx == nyp - 2, 0.0, vn)  # kind "v" wall mask

        # viscous pass (kinds "u","v","u","v": the y-gradients carry the
        # v-point wall mask, mirroring _exchange's kind list)
        gxu = pad_mask(nu * (_ex(un) - un) / dx)
        gyu = pad_mask(nu * (_nx(un) - un) / dy)
        gyu = jnp.where(gidx == nyp - 2, 0.0, gyu)
        gxv = pad_mask(nu * (_ex(vn) - vn) / dx)
        gyv = pad_mask(nu * (_nx(vn) - vn) / dy)
        gyv = jnp.where(gidx == nyp - 2, 0.0, gyv)

        uf = jnp.where(
            interior,
            un + dt * ((gxu - _wx(gxu)) / dx + (gyu - _sx(gyu)) / dy),
            un,
        )
        vf = jnp.where(
            interior,
            vn + dt * ((gxv - _wx(gxv)) / dx + (gyv - _sx(gyv)) / dy),
            vn,
        )
        uf, vf = wrapc(uf), wrapc(vf)
        vf = jnp.where(gidx == nyp - 2, 0.0, vf)

        # the input windows are fully consumed — reuse them as staging for
        # the results, then DMA the T output rows out of each (Mosaic can
        # dynamic-slice refs for DMA, not values)
        off = (out_t - in_t) * 8
        hw[...] = hn
        uw[...] = uf
        vw[...] = vf
        dhw[...] = jnp.where(interior, dh_new, 0.0)
        duw[...] = jnp.where(interior, du_new, 0.0)
        dvw[...] = jnp.where(interior, dv_new, 0.0)

        stores = [
            pltpu.make_async_copy(
                src.at[pl.ds(off, T)], dst.at[pl.ds(out_start, T)],
                out_sems.at[j],
            )
            for j, (src, dst) in enumerate(
                [(hw, ho_hbm), (uw, uo_hbm), (vw, vo_hbm),
                 (dhw, dho_hbm), (duw, duo_hbm), (dvw, dvo_hbm)]
            )
        ]
        for c in stores:
            c.start()
        for c in stores:
            c.wait()

    return kernel


def _tiling(nyp: int, tile_rows: int):
    """(T, R, nyp_pad) for a logical row count — all multiples of 8."""
    T = max(8, (tile_rows // 8) * 8)
    nyp_pad = -(-nyp // T) * T
    R = min(T + 2 * HALO_ROWS, nyp_pad)
    return T, R, nyp_pad


def _col_pad(X: int) -> int:
    return -(-X // 128) * 128


def pad_rows(state, *, tile_rows: int = 16):
    """Zero-pad every field to the kernel's aligned block shape: rows up
    to a multiple of the row tile, columns up to a multiple of 128 (the
    Mosaic lane tiling).

    The padded rows/columns live beyond the ``gidx >= nyp - 1`` ghost
    mask / ``col <= nx`` interior mask: the kernel writes zeros there
    every step, so padding once outside the time loop is sound (and
    avoids 12 extra array copies per step).
    """
    nyp, X = state[0].shape
    _, _, nyp_pad = _tiling(nyp, tile_rows)
    Xp = _col_pad(X)
    if (nyp_pad, Xp) == (nyp, X):
        return state
    return type(state)(
        *(jnp.pad(f, [(0, nyp_pad - nyp), (0, Xp - X)]) for f in state)
    )


def unpad_rows(state, logical_shape):
    nyp, X = logical_shape
    if state[0].shape == (nyp, X):
        return state
    return type(state)(*(f[:nyp, :X] for f in state))


def fused_step(state, params, *, first: bool, interpret=None,
               tile_rows: int = 16, logical_shape=None):
    """One full shallow-water step as a single Pallas kernel.

    ``state`` fields are single-block padded arrays ``(ny+2, nx+2)`` with
    valid ghosts (the step_fn invariant).  Returns the next state with the
    same invariant.  ``first=True`` is the Euler bootstrap (AB with
    a=1, b=0, matching ``_step_local(first=True)``).

    ``logical_shape``: when given, ``state`` is already alignment-padded
    via ``pad_rows`` and the padded state is returned (the time-loop
    fast path); when None, padding/unpadding happens here.
    """
    if logical_shape is None:
        shape = state[0].shape
        out = fused_step(
            pad_rows(state, tile_rows=tile_rows), params, first=first,
            interpret=interpret, tile_rows=tile_rows, logical_shape=shape,
        )
        return unpad_rows(out, shape)

    nyp, X = logical_shape
    nyp_pad, Xp = state[0].shape
    T, R, expect_pad = _tiling(nyp, tile_rows)
    if (nyp_pad, Xp) != (expect_pad, _col_pad(X)):  # pragma: no cover
        raise ValueError(
            f"state shape {state[0].shape} != padded shape "
            f"({expect_pad}, {_col_pad(X)}) for logical {logical_shape} "
            "(use pad_rows with the same tile_rows)"
        )
    p = params
    kern = _make_step_kernel(
        nyp=nyp, nyp_pad=nyp_pad, X=X, Xp=Xp, T=T, R=R,
        dx=p.dx, dy=p.dy, g=p.gravity, nu=p.viscosity, dt=p.dt,
        f0=p.coriolis_f, beta=p.coriolis_beta,
        ab_a=1.0 if first else p.ab_a,
        ab_b=0.0 if first else p.ab_b,
    )
    ntiles = nyp_pad // T
    struct = jax.ShapeDtypeStruct((nyp_pad, Xp), jnp.float32)
    outs = pl.pallas_call(
        kern,
        grid=(ntiles,),
        out_shape=(struct,) * 6,
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)] * 6,
        out_specs=(pl.BlockSpec(memory_space=pl.ANY),) * 6,
        scratch_shapes=(
            [pltpu.VMEM((R, Xp), jnp.float32)] * 6
            + [pltpu.SemaphoreType.DMA((6,)), pltpu.SemaphoreType.DMA((6,))]
        ),
        interpret=_interpret(interpret),
    )(*(f.astype(jnp.float32) for f in state))
    return type(state)(*outs)
