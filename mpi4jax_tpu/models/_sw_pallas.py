"""Fused Pallas step kernel for the single-block shallow-water solver.

The XLA step (`shallow_water._step_local`) materializes ~a dozen
intermediate fields per step (fe/fn/q/ke, viscous gradients, pads, ghost
updates) — on one chip each is a full HBM round-trip, and the step is
bandwidth-bound.  This kernel computes the ENTIRE step — flux/vorticity
build, Adams–Bashforth update, wall + periodic-wrap boundary handling,
and the viscous pass — inside VMEM row-tiles: 6 field reads + 6 field
writes of HBM traffic per step, nothing else.

Scope: single-block grids (1×1 ``ProcessGrid``) with ``periodic_x=True``
— exactly the dense per-chip core.  Decomposed grids keep the XLA path,
where the halo exchanges between sub-steps are the multi-chip collectives
(the kernel's row-window trick cannot see a neighbor *rank*'s rows).

Numerical contract: identical stencils to ``_step_local`` (same Sadourny
C-grid expressions, same boundary-mask ordering as ``_exchange``'s
kinds), so results match the XLA path to f32 reassociation tolerance —
asserted by ``tests/models/test_sw_pallas.py``.

Window discipline: each grid step processes ``T`` output rows from an
``R = T + 16``-row input window (clamped at the array edges).  Every
derived level consumes one neighbor row, and the chain
fe/fn/q/ke → d*_new → AB state → viscous gradients → final state is four
levels deep on each side, so 8 halo rows per side is ample.  Rows that
fall outside the domain are repaired by the ghost-row masks (walls in
y), so windows touching the domain edge stay valid all the way out.

Alignment discipline (Mosaic): HBM refs are (8, 128)-tiled, and dynamic
DMA slice starts in the row dimension must be provably divisible by 8.
Row counts are therefore padded up to a multiple of the row tile ``T``
(itself a multiple of 8) *before* the kernel — see ``pad_rows`` /
``unpad_rows`` — so that every window start ``clip(i*T - 8, 0,
nyp_pad - R)``, output start ``i*T``, and staging offset is a multiple
of 8.  The padded rows sit beyond the ``gidx >= nyp - 1`` ghost mask
and stay identically zero across steps.  (Round 1 shipped unaligned
starts ≡ 4 (mod 8) and failed Mosaic compilation on real TPUs —
VERDICT.md weak #1; this layout is the fix.)
"""

from __future__ import annotations


import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..ops import flash as _flash

HALO_ROWS = 8  # stencil chain is 4 deep per side; 8 keeps DMA starts tile-aligned


def _interpret(flag):
    if flag is None:
        from ..ops.flash import target_platform

        flag = target_platform() != "tpu"
    return pltpu.InterpretParams() if flag else False


# window shift helpers: value at (r, c) of the result reads the neighbor
# of (r, c) in the argument; window-edge garbage is absorbed by the halo
# rows / rebuilt ghost columns.
def _ex(a):  # east: col + 1
    return jnp.concatenate([a[:, 1:], a[:, -1:]], axis=1)


def _wx(a):  # west: col - 1
    return jnp.concatenate([a[:, :1], a[:, :-1]], axis=1)


def _nx(a):  # north: row + 1
    return jnp.concatenate([a[1:], a[-1:]], axis=0)


def _sx(a):  # south: row - 1
    return jnp.concatenate([a[:1], a[:-1]], axis=0)


def _make_step_kernel(*, nyp, nyp_pad, X, Xp, T, R, dx, dy, g, nu, dt,
                      f0, beta, ab_a, ab_b, fuse):
    # X is the logical block width (nx + 2 ghosts); Xp >= X is the
    # 128-aligned padded width the VMEM windows actually carry.  Columns
    # >= X are alignment padding, kept identically zero.
    nx = X - 2
    halo = HALO_ROWS * fuse

    def wrapc(a):
        # periodic-x ghost columns from the interior columns (full height,
        # matching the exchange's full-column wrap strips); the padding
        # tail passes through unchanged (zeros)
        parts = [a[:, nx:nx + 1], a[:, 1:X - 1], a[:, 1:2]]
        if Xp > X:
            parts.append(a[:, X:])
        return jnp.concatenate(parts, axis=1)

    def kernel(ht, hc_, hb, ut, uc_, ub, vt, vc_, vb,
               dht, dhc, dhb, dut, duc, dub, dvt, dvc, dvb,
               ho, uo, vo, dho, duo, dvo):
        # Each field arrives as three pipelined blocks: a halo-row block
        # above, the T-row body, and a halo-row block below (index maps
        # clamp at the array edges).  Stitching them in VMEM gives an
        # R = T + 2*halo row window, and the fetches ride Pallas's grid
        # pipeline, which double-buffers them against compute — round 2's
        # manual-DMA version serialized load -> compute -> store and left
        # the HBM engines idle during compute (VERDICT.md weak #2).
        i = pl.program_id(0)

        def window(top, cur, bot):
            return jnp.concatenate([top[...], cur[...], bot[...]], axis=0)

        h = window(ht, hc_, hb)
        u = window(ut, uc_, ub)
        v = window(vt, vc_, vb)
        dh = window(dht, dhc, dhb)
        du = window(dut, duc, dub)
        dv = window(dvt, dvc, dvb)

        # positional global row index of each window row; the top halo of
        # tile 0 (and the bottom halo of the last tile) holds clamped
        # duplicate rows, but their positional indices fall outside
        # [1, nyp-2] so every derived level masks them as ghosts and the
        # duplicated content is never consumed
        gidx = (i * T - halo) + lax.broadcasted_iota(
            jnp.int32, (R, Xp), 0)
        # <= 0 masks the out-of-domain positional rows of tile 0's halo;
        # >= nyp - 1 masks both walls and the alignment-padding rows
        ghost_row = (gidx <= 0) | (gidx >= nyp - 1)
        col = lax.broadcasted_iota(jnp.int32, (R, Xp), 1)
        interior = (~ghost_row) & (col >= 1) & (col <= nx)

        def pad_mask(a):
            # _pad semantics: ghost ring zero (x-ghosts rebuilt by wrapc)
            return wrapc(jnp.where(ghost_row, 0.0, a))

        def advance(h, u, v, dh, du, dv):
            """One full time step on the VMEM window.

            Valid interior values shrink by HALO_ROWS window rows per
            application (the stencil chain is 4 levels deep; 8 rows is
            ample), so ``fuse`` applications leave the T body rows exact.
            """
            # hc: h's interior with edge-copied ghost rows
            # (jnp.pad mode="edge")
            hc = jnp.where(gidx == 0, _nx(h), h)
            hc = jnp.where(gidx == nyp - 1, _sx(hc), hc)
            hc = wrapc(hc)

            # flux / vorticity / kinetic-energy fields (interior
            # expressions; ghosts = _pad zeros + exchange: x-wrap, fn
            # gets the v-point wall)
            fe = pad_mask(0.5 * (hc + _ex(hc)) * u)
            fn = pad_mask(0.5 * (hc + _nx(hc)) * v)
            fn = jnp.where(gidx == nyp - 2, 0.0, fn)  # kind "v" wall mask
            y = (gidx - 1).astype(jnp.float32) * dy
            f = f0 + beta * y
            zeta = (_ex(v) - v) / dx - (_nx(u) - u) / dy
            thick = 0.25 * (hc + _ex(hc) + _nx(hc) + _nx(_ex(hc)))
            q = pad_mask((f + zeta) / thick)
            ke = pad_mask(0.5 * (0.5 * (u ** 2 + _wx(u) ** 2)
                                 + 0.5 * (v ** 2 + _sx(v) ** 2)))

            # tendencies
            dh_new = -(fe - _wx(fe)) / dx - (fn - _sx(fn)) / dy
            du_new = (-g * (_ex(h) - h) / dx
                      + 0.5 * (q * 0.5 * (fn + _ex(fn))
                               + _sx(q) * 0.5 * (_sx(fn) + _sx(_ex(fn))))
                      - (_ex(ke) - ke) / dx)
            dv_new = (-g * (_nx(h) - h) / dy
                      - 0.5 * (q * 0.5 * (fe + _nx(fe))
                               + _wx(q) * 0.5 * (_wx(fe) + _nx(_wx(fe))))
                      - (_nx(ke) - ke) / dy)

            # Adams–Bashforth update (interior), ghosts keep the BC values
            hn = jnp.where(interior, h + dt * (ab_a * dh_new + ab_b * dh), h)
            un = jnp.where(interior, u + dt * (ab_a * du_new + ab_b * du), u)
            vn = jnp.where(interior, v + dt * (ab_a * dv_new + ab_b * dv), v)
            hn, un, vn = wrapc(hn), wrapc(un), wrapc(vn)
            vn = jnp.where(gidx == nyp - 2, 0.0, vn)  # kind "v" wall mask

            # viscous pass (kinds "u","v","u","v": the y-gradients carry
            # the v-point wall mask, mirroring _exchange's kind list)
            gxu = pad_mask(nu * (_ex(un) - un) / dx)
            gyu = pad_mask(nu * (_nx(un) - un) / dy)
            gyu = jnp.where(gidx == nyp - 2, 0.0, gyu)
            gxv = pad_mask(nu * (_ex(vn) - vn) / dx)
            gyv = pad_mask(nu * (_nx(vn) - vn) / dy)
            gyv = jnp.where(gidx == nyp - 2, 0.0, gyv)

            uf = jnp.where(
                interior,
                un + dt * ((gxu - _wx(gxu)) / dx + (gyu - _sx(gyu)) / dy),
                un,
            )
            vf = jnp.where(
                interior,
                vn + dt * ((gxv - _wx(gxv)) / dx + (gyv - _sx(gyv)) / dy),
                vn,
            )
            uf, vf = wrapc(uf), wrapc(vf)
            vf = jnp.where(gidx == nyp - 2, 0.0, vf)
            return (hn, uf, vf,
                    jnp.where(interior, dh_new, 0.0),
                    jnp.where(interior, du_new, 0.0),
                    jnp.where(interior, dv_new, 0.0))

        # temporal blocking: `fuse` full steps per HBM round-trip — the
        # same 6-read/6-write traffic buys fuse steps of evolution
        fields = (h, u, v, dh, du, dv)
        for _ in range(fuse):
            fields = advance(*fields)

        # store the T body rows; halo rows were computed only to feed the
        # stencil chain
        sl = slice(halo, halo + T)
        for ref, val in zip((ho, uo, vo, dho, duo, dvo), fields):
            ref[...] = val[sl]

    return kernel


def _tiling(nyp: int, tile_rows: int, fuse: int = 1):
    """(T, R, nyp_pad) for a logical row count — all multiples of the
    halo height ``8 * fuse`` (the body must tile evenly into halo-block
    units for the clamped index maps)."""
    halo = HALO_ROWS * fuse
    T = max(halo, (tile_rows // halo) * halo)
    nyp_pad = -(-nyp // T) * T
    R = T + 2 * halo
    return T, R, nyp_pad


def _col_pad(X: int) -> int:
    return -(-X // 128) * 128


def pad_rows(state, *, tile_rows: int = 16, fuse: int = 1):
    """Zero-pad every field to the kernel's aligned block shape: rows up
    to a multiple of the row tile, columns up to a multiple of 128 (the
    Mosaic lane tiling).

    The padded rows/columns live beyond the ``gidx >= nyp - 1`` ghost
    mask / ``col <= nx`` interior mask: the kernel writes zeros there
    every step, so padding once outside the time loop is sound (and
    avoids 12 extra array copies per step).
    """
    nyp, X = state[0].shape
    _, _, nyp_pad = _tiling(nyp, tile_rows, fuse)
    Xp = _col_pad(X)
    if (nyp_pad, Xp) == (nyp, X):
        return state
    return type(state)(
        *(jnp.pad(f, [(0, nyp_pad - nyp), (0, Xp - X)]) for f in state)
    )


def unpad_rows(state, logical_shape):
    nyp, X = logical_shape
    if state[0].shape == (nyp, X):
        return state
    return type(state)(*(f[:nyp, :X] for f in state))


def fused_step(state, params, *, first: bool, interpret=None,
               tile_rows: int = 16, logical_shape=None, fuse: int = 1):
    """``fuse`` full shallow-water steps as a single Pallas kernel.

    ``state`` fields are single-block padded arrays ``(ny+2, nx+2)`` with
    valid ghosts (the step_fn invariant).  Returns the state ``fuse``
    steps later with the same invariant.  ``first=True`` is the Euler
    bootstrap (AB with a=1, b=0, matching ``_step_local(first=True)``;
    requires ``fuse == 1``).

    ``fuse > 1`` is temporal blocking: the halo widens to ``8 * fuse``
    rows and the kernel advances the VMEM window ``fuse`` times before
    touching HBM again, so one 6-read/6-write round-trip (the whole HBM
    cost) is amortized over ``fuse`` steps.

    ``logical_shape``: when given, ``state`` is already alignment-padded
    via ``pad_rows`` (same ``tile_rows``/``fuse``) and the padded state
    is returned (the time-loop fast path); when None, padding/unpadding
    happens here.
    """
    if first and fuse != 1:
        raise ValueError("the Euler bootstrap step requires fuse=1")
    if logical_shape is None:
        shape = state[0].shape
        out = fused_step(
            pad_rows(state, tile_rows=tile_rows, fuse=fuse), params,
            first=first, interpret=interpret, tile_rows=tile_rows,
            logical_shape=shape, fuse=fuse,
        )
        return unpad_rows(out, shape)

    nyp, X = logical_shape
    nyp_pad, Xp = state[0].shape
    T, R, expect_pad = _tiling(nyp, tile_rows, fuse)
    if (nyp_pad, Xp) != (expect_pad, _col_pad(X)):  # pragma: no cover
        raise ValueError(
            f"state shape {state[0].shape} != padded shape "
            f"({expect_pad}, {_col_pad(X)}) for logical {logical_shape} "
            "(use pad_rows with the same tile_rows/fuse)"
        )
    p = params
    kern = _make_step_kernel(
        nyp=nyp, nyp_pad=nyp_pad, X=X, Xp=Xp, T=T, R=R,
        dx=p.dx, dy=p.dy, g=p.gravity, nu=p.viscosity, dt=p.dt,
        f0=p.coriolis_f, beta=p.coriolis_beta,
        ab_a=1.0 if first else p.ab_a,
        ab_b=0.0 if first else p.ab_b,
        fuse=fuse,
    )
    halo = HALO_ROWS * fuse
    ntiles = nyp_pad // T
    tpb = T // halo  # body height in halo-block units
    nblk = nyp_pad // halo
    # three pipelined input blocks per field: top halo, body, bottom halo
    # (index maps clamp at the edges; the kernel's positional ghost masks
    # neutralize the clamped duplicate rows)
    top_spec = pl.BlockSpec(
        (halo, Xp), lambda i: (jnp.maximum(i * tpb - 1, 0), 0))
    body_spec = pl.BlockSpec((T, Xp), lambda i: (i, 0))
    bot_spec = pl.BlockSpec(
        (halo, Xp),
        lambda i: (jnp.minimum(i * tpb + tpb, nblk - 1), 0))
    struct = jax.ShapeDtypeStruct((nyp_pad, Xp), jnp.float32)
    fields = [f.astype(jnp.float32) for f in state]
    outs = pl.pallas_call(
        kern,
        grid=(ntiles,),
        out_shape=(struct,) * 6,
        in_specs=[top_spec, body_spec, bot_spec] * 6,
        out_specs=(body_spec,) * 6,
        # windows past the default 16MB scoped-vmem cap are legal (v5e
        # has 128MB of VMEM); the pipeline needs 2x buffers per block
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary",),
            vmem_limit_bytes=_flash.VMEM_LIMIT_BYTES),
        interpret=_interpret(interpret),
    )(*(f for field in fields for f in (field, field, field)))
    return type(state)(*outs)
