"""World-tier shallow-water: one PROCESS per rank, reference style.

The mesh-tier solver (:mod:`.shallow_water`) decomposes the domain over
a device mesh inside one SPMD program.  This variant is the shape the
reference actually runs — ``mpirun -n N python …`` with a per-rank
program, halo exchange as explicit token-ordered point-to-point over the
communication substrate (/root/reference/examples/shallow_water.py:173-271)
— here over the framework's world tier (native shm/TCP transport), with
every step jitted per rank and the world ops lowered as ordered FFI
custom calls.

All the physics is inherited from :class:`.shallow_water.ShallowWater`;
only the parallel substrate is swapped:

- rank coordinates are static Python ints (per-rank programs may
  branch on rank — the reference's model);
- the halo exchange is one world-tier ``neighbor_exchange`` per
  direction-dim (both strips in one deadlock-free op) with plain wall
  handling at physical boundaries;
- the initial-condition collectives (`scan` along columns, global
  `allreduce`) dispatch to the world tier through the SAME ``ops``
  calls the mesh tier uses — the model code is tier-agnostic through
  the public API, which is the point of the framework.

Launch (the scaling study ``benchmarks/sw_world_rank.py`` wraps this):

    python -m mpi4jax_tpu.runtime.launch -n 4 benchmarks/sw_world_rank.py
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from .. import ops
from ..runtime.transport import WorldComm
from .shallow_water import ShallowWater, SWParams, SWState


class _WorldGrid:
    """The minimal grid surface the model touches in world mode."""

    def __init__(self, comm: WorldComm, shape, coords):
        self.comm = comm
        self.shape = shape
        self.coords = coords
        self._col_comm = None

    def axis_comm(self, dim: int):
        assert dim == 0, "world model only scans along y"
        if self._col_comm is None:
            iy, ix = self.coords
            # ranks sharing a column, ordered south→north by iy
            self._col_comm = self.comm.split(color=ix, key=iy)
        return self._col_comm


class WorldShallowWater(ShallowWater):
    """Per-rank world-tier solver on a ``(gy, gx)`` rank grid."""

    def __init__(self, comm: WorldComm, grid_shape, global_shape,
                 params: Optional[SWParams] = None):
        gy, gx = grid_shape
        if comm.size() != gy * gx:
            raise ValueError(
                f"grid {grid_shape} needs {gy * gx} ranks, world has "
                f"{comm.size()}"
            )
        self.comm = comm
        self.ny, self.nx = global_shape
        if self.ny % gy or self.nx % gx:
            raise ValueError(
                f"domain {global_shape} not divisible by grid {grid_shape}"
            )
        rank = comm.rank()
        # row-major rank grid: rank = iy * gx + ix (iy south→north)
        self.iy, self.ix = rank // gx, rank % gx
        self.gy, self.gx = gy, gx
        self.ny_loc = self.ny // gy
        self.nx_loc = self.nx // gx
        self.params = params or SWParams(dx=5e3, dy=5e3)
        self.block_shape = (self.ny_loc + 2, self.nx_loc + 2)
        self.grid = _WorldGrid(comm, (gy, gx), (self.iy, self.ix))

    # -- substrate overrides ---------------------------------------------
    def _local_coords(self):
        p = self.params
        jy = jnp.arange(-1, self.ny_loc + 1) + self.iy * self.ny_loc
        jx = jnp.arange(-1, self.nx_loc + 1) + self.ix * self.nx_loc
        y = jy.astype(jnp.float32) * p.dy
        x = jx.astype(jnp.float32) * p.dx
        return jnp.meshgrid(y, x, indexing="ij")

    def _neighbor(self, diy, dix):
        """Rank of the (diy, dix) grid neighbor, or None (wall)."""
        iy, ix = self.iy + diy, self.ix + dix
        if not 0 <= iy < self.gy:
            return None
        if not 0 <= ix < self.gx:
            if not self.params.periodic_x:
                return None
            ix %= self.gx
        return iy * self.gx + ix

    def _dir_exchange(self, stack, dim, hi_neighbor, lo_neighbor):
        """Fill ghost strips of the field stack along one array dim.

        ``stack``: (nfields, my+2, mx+2).  Interior strips go to the
        neighbors; what arrives fills the ghosts.  Wall sides keep the
        existing ghost values (the boundary condition) — same contract
        as the mesh tier's ``halo_exchange``.

        Both directions ride ONE ``neighbor_exchange`` op (the
        MPI_Neighbor_alltoall analog): a single blocking point per dim.
        Two earlier schedules failed here and are worth remembering —
        pairing both directions with the SAME neighbor per op deadlocks
        on any periodic ring of >= 3 ranks (each rank's first receive
        matches its neighbor's SECOND send: a cycle ordered per-rank
        execution cannot resolve — found as a silent np=6 hang), and
        two sequential uniform shifts are correct but cost an extra
        blocking wait per dim, i.e. a scheduler quantum per step on
        core-sharing hosts (np=2 regressed 141 s -> 202 s).
        """
        me = self.iy * self.gx + self.ix
        extent = stack.shape[dim + 1]
        lo_int = jax.lax.slice_in_dim(stack, 1, 2, axis=dim + 1)
        hi_int = jax.lax.slice_in_dim(stack, extent - 2, extent - 1,
                                      axis=dim + 1)
        from_above = from_below = None
        if hi_neighbor is None and lo_neighbor is None:
            return stack  # both walls (e.g. y on a (1, N) grid): no comm
        if hi_neighbor == me and lo_neighbor == me:
            # self-wrap: the high ghost wraps around to the LOW interior
            # strip and vice versa (mesh tier's n==1 periodic case)
            from_above, from_below = lo_int, hi_int
        else:
            # one op for both directions: a single blocking point per
            # dim — on core-sharing hosts every extra blocking wait
            # costs a scheduler quantum, which dominated the two-shift
            # schedule (and any per-neighbor pairing of both directions
            # deadlocks on rings >= 3; see neighbor_exchange)
            from_below, from_above = ops.neighbor_exchange(
                lo_int, hi_int, lo=lo_neighbor, hi=hi_neighbor,
                comm=self.comm, tag=60 + 2 * dim,
            )
            if lo_neighbor is None:
                from_below = None  # wall: keep existing ghost values
            if hi_neighbor is None:
                from_above = None
        if from_above is not None:
            start = [0] * stack.ndim
            start[dim + 1] = extent - 1
            stack = jax.lax.dynamic_update_slice(
                stack, from_above.astype(stack.dtype), start
            )
        if from_below is not None:
            start = [0] * stack.ndim
            stack = jax.lax.dynamic_update_slice(
                stack, from_below.astype(stack.dtype), start
            )
        return stack

    def _exchange(self, fields, kinds):
        p = self.params
        stack = jnp.stack(fields)  # one message per direction, all fields
        # y (array dim 0): high side = north neighbor (iy+1)
        stack = self._dir_exchange(
            stack, 0, self._neighbor(+1, 0), self._neighbor(-1, 0)
        )
        # x (array dim 1)
        stack = self._dir_exchange(
            stack, 1, self._neighbor(0, +1), self._neighbor(0, -1)
        )
        at_north = self.iy == self.gy - 1
        at_east = self.ix == self.gx - 1
        result = []
        for f, kind in zip(stack, kinds):
            if kind == "v" and at_north:
                f = f.at[-2, :].set(0.0)
            elif kind == "u" and not p.periodic_x and at_east:
                f = f.at[:, -2].set(0.0)
            result.append(f)
        return result

    # -- drivers (no shard_map: the process IS the rank) ------------------
    def _spmd(self, fn, out_specs=None):
        del out_specs
        return fn

    def init(self) -> SWState:
        fn = getattr(self, "_init_fn", None)
        if fn is None:
            fn = jax.jit(lambda: self._initial_local())
            self._init_fn = fn
        return fn()

    def step_fn(self, n_steps: int, first: bool = False,
                donate: bool = False, impl: str = "xla",
                tile_rows: int = 120, fuse: int = 3):
        if impl not in ("auto", "xla"):
            raise ValueError(
                "world-tier solver runs the XLA slice-stencil step "
                "(the Pallas fused kernel is a single-chip mesh path)"
            )

        def steps(state):
            if first:
                state = self._step_local(state, first=True)
                remaining = n_steps - 1
            else:
                remaining = n_steps
            if remaining > 0:
                state = jax.lax.scan(
                    lambda s, _: (self._step_local(s, first=False), ()),
                    state, None, length=remaining,
                )[0]
            return state

        return jax.jit(steps, donate_argnums=0 if donate else ())

    def interior(self, f):
        return f[1:-1, 1:-1]

    def gather_global(self, f):
        """Full-domain field on rank 0 (the reference's solution gather,
        its shallow_water.py:588): world gather + block reassembly."""
        rows = ops.gather(self.interior(f), root=0, comm=self.comm)
        if self.comm.rank() != 0:
            return None
        import numpy as np

        blocks = np.asarray(rows).reshape(
            self.gy, self.gx, self.ny_loc, self.nx_loc
        )
        return np.block(
            [[blocks[iy, ix] for ix in range(self.gx)]
             for iy in range(self.gy)]
        )
