"""Distributed 3-D FFT via slab decomposition + alltoall transpose.

The reference's alltoall exists precisely for this pattern — the
"FFT/spectral slab transpose" (SURVEY.md §2.4, alltoall.py:39-83 there) —
but ships no FFT machinery.  Here the full component, TPU-first: local FFTs
are XLA-fused ``jnp.fft`` batches, and the global transpose is a single
``lax.all_to_all`` riding ICI bisection bandwidth.

Decomposition: a field ``(X, Y, Z)`` is slab-sharded over the first axis
(``X_local = X/size``).  ``fft3`` returns the spectrum slab-sharded over
**Y** (the standard pencil handoff); ``ifft3`` returns to X-sharded.

A Poisson solver (``∇²u = f`` with periodic BCs) demonstrates the spectral
workflow end-to-end and anchors the correctness tests.
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax


def _transpose_x_to_y(x, axis):
    """(X_loc, Y, Z) x-sharded → (X, Y_loc, Z) y-sharded (one all_to_all)."""
    size = lax.axis_size(axis)
    xl, y, z = x.shape
    if y % size:
        raise ValueError(f"Y ({y}) must be divisible by axis size {size}")
    yl = y // size
    # destination-major leading axis for all_to_all
    t = x.reshape(xl, size, yl, z).transpose(1, 0, 2, 3)
    t = lax.all_to_all(t, axis, split_axis=0, concat_axis=0)
    # rows are source ranks = X blocks, in rank order → concat along X
    return t.reshape(size * xl, yl, z)


def _transpose_y_to_x(x, axis):
    """Inverse of :func:`_transpose_x_to_y`."""
    size = lax.axis_size(axis)
    xg, yl, z = x.shape
    if xg % size:
        raise ValueError(f"X ({xg}) must be divisible by axis size {size}")
    xl = xg // size
    t = x.reshape(size, xl, yl, z)
    t = lax.all_to_all(t, axis, split_axis=0, concat_axis=0)
    # rows are source ranks = Y blocks → concat along Y
    return t.transpose(1, 0, 2, 3).reshape(xl, size * yl, z)


def fft3(x, *, axis):
    """3-D FFT of an X-slab-sharded real/complex field.

    Input ``(X_local, Y, Z)``; output ``(X, Y_local, Z)`` complex spectrum,
    Y-slab-sharded.
    """
    x = jnp.asarray(x, jnp.complex64 if x.dtype != jnp.complex128 else x.dtype)
    x = jnp.fft.fftn(x, axes=(1, 2))        # local Y, Z transforms
    x = _transpose_x_to_y(x, axis)           # single alltoall
    return jnp.fft.fft(x, axis=0)            # now-local X transform


def ifft3(x, *, axis):
    """Inverse of :func:`fft3`: Y-sharded spectrum → X-sharded field."""
    x = jnp.fft.ifft(x, axis=0)
    x = _transpose_y_to_x(x, axis)
    return jnp.fft.ifftn(x, axes=(1, 2))


def wavenumbers(n: int, d: float = 1.0):
    return 2 * np.pi * np.fft.fftfreq(n, d=d)


def poisson_solve(f, *, axis, shape, lengths=(2 * np.pi,) * 3):
    """Solve ``∇²u = f`` with periodic boundaries, spectrally.

    ``f``: (X_local, Y, Z) real slab.  Returns the zero-mean solution with
    the same sharding.
    """
    nx, ny, nz = shape
    lx, ly, lz = lengths
    size = lax.axis_size(axis)
    idx = lax.axis_index(axis)

    spec = fft3(f, axis=axis)  # (X, Y_local, Z), Y-sharded

    kx = jnp.asarray(wavenumbers(nx, lx / nx))            # full X axis
    ky_full = jnp.asarray(wavenumbers(ny, ly / ny))
    yl = ny // size
    ky = lax.dynamic_slice(ky_full, (idx * yl,), (yl,))    # this Y slab
    kz = jnp.asarray(wavenumbers(nz, lz / nz))

    k2 = (
        kx[:, None, None] ** 2
        + ky[None, :, None] ** 2
        + kz[None, None, :] ** 2
    )
    inv = jnp.where(k2 > 0, -1.0 / jnp.maximum(k2, 1e-30), 0.0)
    u_spec = spec * inv  # zero-mode dropped → zero-mean solution
    return ifft3(u_spec, axis=axis).real
