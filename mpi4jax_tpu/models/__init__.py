from .shallow_water import ShallowWater, SWParams, SWState  # noqa: F401
