"""Distributed nonlinear shallow-water model (the framework's flagship app).

Physical setup matches the reference demo so benchmarks are comparable
(/root/reference/examples/shallow_water.py: Sadourny energy-conserving
C-grid scheme, geostrophic-jet initial condition, Adams–Bashforth-2 with
offset 0.1, CFL dt = 0.125·dx/√(gH), periodic in x, walls in y, lateral
viscosity 1e-3·f·dx²).  The *implementation* is TPU-first and shares no
structure with it:

- 2-D domain decomposition is a ``ProcessGrid`` over a device mesh; each
  halo update is a *batched* ``lax.ppermute`` (several fields stacked into
  one collective per direction) instead of the reference's ~10 token-chained
  single-field sendrecv calls per step (shallow_water.py:277-412 there) —
  fewer, larger ICI transfers (SURVEY.md §7 hard part 2).
- The time loop is ``lax.fori_loop`` inside one ``shard_map``-ped jit.
- The distributed initial condition uses the framework's own collectives:
  the geostrophic height profile is a *global* cumulative integral along y,
  computed as local cumsum + exclusive cross-rank prefix via ``scan`` —
  plus mean-centering via ``psum``.
- Stencils are slice-expressions on halo-padded blocks (C-grid):
  interior = a[1:-1, 1:-1]; east = a[1:-1, 2:]; north = a[2:, 1:-1].
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from .. import ops
from ..parallel.grid import ProcessGrid
from ..parallel.halo import halo_exchange


class SWParams(NamedTuple):
    dx: float
    dy: float
    gravity: float = 9.81
    depth: float = 100.0
    coriolis_f: float = 2e-4
    coriolis_beta: float = 2e-11
    day_seconds: float = 86_400.0
    ab_a: float = 1.6  # Adams–Bashforth 1.5 + offset
    ab_b: float = -0.6
    periodic_x: bool = True

    @property
    def dt(self) -> float:
        return 0.125 * min(self.dx, self.dy) / float(
            np.sqrt(self.gravity * self.depth)
        )

    @property
    def viscosity(self) -> float:
        return 1e-3 * self.coriolis_f * self.dx**2


class SWState(NamedTuple):
    h: jax.Array
    u: jax.Array
    v: jax.Array
    dh: jax.Array
    du: jax.Array
    dv: jax.Array


# stencil views on a 1-cell halo-padded block
def _C(a):
    return a[..., 1:-1, 1:-1]


def _E(a):
    return a[..., 1:-1, 2:]


def _W(a):
    return a[..., 1:-1, :-2]


def _N(a):
    return a[..., 2:, 1:-1]


def _S(a):
    return a[..., :-2, 1:-1]


def _NE(a):
    return a[..., 2:, 2:]


def _SE(a):
    return a[..., :-2, 2:]


def _NW(a):
    return a[..., 2:, :-2]


def _pad(interior):
    return jnp.pad(interior, [(1, 1), (1, 1)])


def _embed(old, interior):
    """Write a new interior into ``old``, preserving its ghost ring (the
    physical-wall ghost values must persist across steps; the exchange
    refreshes only interior-facing ghosts)."""
    return old.at[1:-1, 1:-1].set(interior)


class ShallowWater:
    """Shallow-water solver over a 2-D process grid.

    ``global_shape = (ny, nx)`` is the physical (unpadded) domain; each rank
    owns a ``(ny/gy + 2, nx/gx + 2)`` halo-padded block.
    """

    def __init__(
        self,
        grid: ProcessGrid,
        global_shape,
        params: Optional[SWParams] = None,
    ):
        self.grid = grid
        self.ny, self.nx = global_shape
        gy, gx = grid.shape
        if self.ny % gy or self.nx % gx:
            raise ValueError(
                f"domain {global_shape} not divisible by grid {grid.shape}"
            )
        self.ny_loc = self.ny // gy
        self.nx_loc = self.nx // gx
        self.params = params or SWParams(dx=5e3, dy=5e3)
        self.block_shape = (self.ny_loc + 2, self.nx_loc + 2)
        # stacked-block global shapes for shard_map I/O
        self.stacked_shape = (
            gy * self.block_shape[0],
            gx * self.block_shape[1],
        )

    # -- per-rank coordinate fields (inside shard_map) --------------------
    def _local_coords(self):
        p = self.params
        iy = lax.axis_index(self.grid.axes[0])
        ix = lax.axis_index(self.grid.axes[1])
        # halo-inclusive index ranges, offset by this rank's block origin
        jy = jnp.arange(-1, self.ny_loc + 1) + iy * self.ny_loc
        jx = jnp.arange(-1, self.nx_loc + 1) + ix * self.nx_loc
        y = jy.astype(jnp.float32) * p.dy
        x = jx.astype(jnp.float32) * p.dx
        return jnp.meshgrid(y, x, indexing="ij")

    def _coriolis(self, yy):
        p = self.params
        return p.coriolis_f + yy * p.coriolis_beta

    # -- boundary handling ------------------------------------------------
    def _exchange(self, fields, kinds):
        """Batched halo exchange + physical wall conditions.

        ``kinds``: per-field C-grid location "h" | "u" | "v" — v-point
        fields get the no-normal-flow wall at the north boundary, u-point
        fields a wall at east when x is not periodic (reference behavior:
        enforce_boundaries' trailing wall masks).
        """
        p = self.params
        out = halo_exchange(
            tuple(fields),
            self.grid,
            halo=1,
            periodic=(False, p.periodic_x),
        )
        gy_ax, gx_ax = self.grid.axes
        at_north = lax.axis_index(gy_ax) == lax.axis_size(gy_ax) - 1
        at_east = lax.axis_index(gx_ax) == lax.axis_size(gx_ax) - 1
        result = []
        for f, kind in zip(out, kinds):
            if kind == "v":
                f = f.at[-2, :].set(jnp.where(at_north, 0.0, f[-2, :]))
            elif kind == "u" and not p.periodic_x:
                f = f.at[:, -2].set(jnp.where(at_east, 0.0, f[:, -2]))
            result.append(f)
        return result

    # -- initial conditions ----------------------------------------------
    def _initial_local(self):
        """Geostrophic jet (reference setup) via distributed collectives."""
        p = self.params
        yy, xx = self._local_coords()
        ly = self.ny * p.dy
        lx = self.nx * p.dx

        u0 = 10.0 * jnp.exp(-((yy - 0.5 * ly) ** 2) / (0.02 * lx) ** 2)
        v0 = jnp.zeros_like(u0)

        # h in geostrophic balance: h(y) = -(1/g)∫ f·u dy — a global prefix
        # integral along y.  Local cumsum + exclusive cross-rank prefix sum.
        integrand = -p.dy * u0 * self._coriolis(yy) / p.gravity
        body = integrand[1:-1, 1:-1]  # interior rows only
        local_cum = jnp.cumsum(body, axis=0)
        col_total = local_cum[-1]
        incl = ops.scan(col_total, op=ops.SUM, comm=self.grid.axis_comm(0))
        offset = incl - col_total  # exclusive prefix from ranks above... south
        h_int = local_cum + offset[None, :]

        # center around the resting depth (global mean over the interior)
        total = ops.allreduce(
            jnp.sum(h_int), op=ops.SUM, comm=self.grid.comm
        )
        h_int = h_int - total / float(self.ny * self.nx)

        h_int = (
            p.depth
            + h_int
            + 0.2
            * jnp.sin(_C(xx) / lx * 10 * jnp.pi)
            * jnp.cos(_C(yy) / ly * 8 * jnp.pi)
        )

        # edge-pad: physical-wall ghosts continue the boundary value (zero
        # normal gradient), interior ghosts are replaced by the exchange
        h0 = jnp.pad(h_int, 1, mode="edge")
        h0, u0, v0 = self._exchange((h0, u0, v0), ("h", "u", "v"))
        zero = jnp.zeros(self.block_shape, jnp.float32)
        return SWState(h0, u0, v0, zero, zero, zero)

    # -- dynamics ---------------------------------------------------------
    def _step_local(self, state: SWState, first: bool) -> SWState:
        p = self.params
        dt = p.dt
        dx, dy, g = p.dx, p.dy, p.gravity
        h, u, v, dh, du, dv = state

        # h with edge-valued ghosts: physical-wall ghost rows keep the edge
        # value, interior ghosts are overwritten by the exchange.
        (hc,) = self._exchange((jnp.pad(_C(h), 1, mode="edge"),), ("h",))

        # fe/fn/q/ke all derive from (hc, u, v) whose ghosts are already
        # valid — compute them together and exchange in ONE batched
        # collective per direction (the reference interleaves four separate
        # token-ordered exchanges here, shallow_water.py:277-345 there)
        fe = _pad(0.5 * (_C(hc) + _E(hc)) * _C(u))
        fn = _pad(0.5 * (_C(hc) + _N(hc)) * _C(v))
        yy, _ = self._local_coords()
        zeta = (_E(v) - _C(v)) / dx - (_N(u) - _C(u)) / dy
        thickness = 0.25 * (_C(hc) + _E(hc) + _N(hc) + _NE(hc))
        q = _pad((self._coriolis(_C(yy)) + zeta) / thickness)
        ke = _pad(
            0.5
            * (
                0.5 * (_C(u) ** 2 + _W(u) ** 2)
                + 0.5 * (_C(v) ** 2 + _S(v) ** 2)
            )
        )
        fe, fn, q, ke = self._exchange(
            (fe, fn, q, ke), ("u", "v", "h", "h")
        )

        dh_new = -(_C(fe) - _W(fe)) / dx - (_C(fn) - _S(fn)) / dy

        du_new = -g * (_E(h) - _C(h)) / dx + 0.5 * (
            _C(q) * 0.5 * (_C(fn) + _E(fn))
            + _S(q) * 0.5 * (_S(fn) + _SE(fn))
        )
        dv_new = -g * (_N(h) - _C(h)) / dy - 0.5 * (
            _C(q) * 0.5 * (_C(fe) + _N(fe))
            + _W(q) * 0.5 * (_W(fe) + _NW(fe))
        )
        du_new = du_new - (_E(ke) - _C(ke)) / dx
        dv_new = dv_new - (_N(ke) - _C(ke)) / dy

        if first:
            h = _embed(h, _C(h) + dt * dh_new)
            u = _embed(u, _C(u) + dt * du_new)
            v = _embed(v, _C(v) + dt * dv_new)
        else:
            h = _embed(h, _C(h) + dt * (p.ab_a * dh_new + p.ab_b * _C(dh)))
            u = _embed(u, _C(u) + dt * (p.ab_a * du_new + p.ab_b * _C(du)))
            v = _embed(v, _C(v) + dt * (p.ab_a * dv_new + p.ab_b * _C(dv)))
        h, u, v = self._exchange((h, u, v), ("h", "u", "v"))

        if p.viscosity > 0:
            nu = p.viscosity
            gx_u = _pad(nu * (_E(u) - _C(u)) / dx)
            gy_u = _pad(nu * (_N(u) - _C(u)) / dy)
            gx_v = _pad(nu * (_E(v) - _C(v)) / dx)
            gy_v = _pad(nu * (_N(v) - _C(v)) / dy)
            gx_u, gy_u, gx_v, gy_v = self._exchange(
                (gx_u, gy_u, gx_v, gy_v), ("u", "v", "u", "v")
            )
            u = _embed(
                u,
                _C(u)
                + dt
                * (
                    (_C(gx_u) - _W(gx_u)) / dx
                    + (_C(gy_u) - _S(gy_u)) / dy
                ),
            )
            v = _embed(
                v,
                _C(v)
                + dt
                * (
                    (_C(gx_v) - _W(gx_v)) / dx
                    + (_C(gy_v) - _S(gy_v)) / dy
                ),
            )
            h, u, v = self._exchange((h, u, v), ("h", "u", "v"))

        return SWState(
            h, u, v, _pad(dh_new), _pad(du_new), _pad(dv_new)
        )

    # -- public driver ----------------------------------------------------
    def _spmd(self, fn, out_specs=None):
        spec = P(*self.grid.axes)
        return jax.shard_map(
            fn,
            mesh=self.grid.mesh,
            in_specs=spec,
            out_specs=out_specs if out_specs is not None else spec,
            check_vma=False,
        )

    def init(self) -> SWState:
        """Initial state as stacked-block global arrays."""
        # cache the jitted builder: a fresh jax.jit wrapper per call
        # would retrace AND recompile every time (2.4 s/call through the
        # tunnel's remote compile helper, bench r3)
        fn = getattr(self, "_init_fn", None)
        if fn is None:

            def go(dummy):
                del dummy
                # local blocks are concatenated along both grid axes by
                # out_specs, yielding stacked-block global arrays directly
                return self._initial_local()

            fn = jax.jit(
                self._spmd(go, out_specs=SWState(*(P(*self.grid.axes),) * 6))
            )
            self._init_fn = fn

        dummy = jnp.zeros(
            (self.grid.shape[0], self.grid.shape[1]), jnp.float32
        )
        return fn(dummy)

    def step_fn(self, n_steps: int, first: bool = False,
                donate: bool = False, impl: str = "auto",
                tile_rows: int = 120, fuse: int = 3):
        """A jitted function advancing the stacked-block state n_steps.

        ``donate=True`` donates the input state's buffers to the output
        (callers must not reuse the argument after the call) — saves one
        state-sized allocation per invocation on HBM-bound configs.

        ``impl``: "xla" — slice-stencil step (`_step_local`, works on any
        grid); "pallas" — the fused single-kernel step
        (`_sw_pallas.fused_step`, single-block periodic-x grids only:
        6 reads + 6 writes of HBM per step instead of ~a dozen
        materialized intermediates); "auto" — pallas when eligible, with
        an automatic fall-back to the XLA step if the kernel fails to
        compile on the local backend (a default path must never break a
        working config — VERDICT.md weak #1).

        ``tile_rows``/``fuse`` tune the Pallas path: row-tile height and
        temporal blocking factor (``fuse`` steps per HBM round-trip —
        see ``_sw_pallas.fused_step``).  Defaults tuned on a v5e at the
        flagship (1800, 3600) size: 120/3 ≈ 0.69 ms/step; larger
        windows (144/3, 128/4, 120/5) overflow what the Mosaic compiler
        will build.
        """
        gy, gx = self.grid.shape
        if impl not in ("auto", "xla", "pallas"):
            raise ValueError(f"unknown impl {impl!r}")
        eligible = (gy, gx) == (1, 1) and self.params.periodic_x
        if impl == "pallas" and not eligible:
            raise ValueError(
                "impl='pallas' needs a 1x1 grid with periodic_x=True"
            )
        if impl == "auto" and eligible:
            # compiled-kernel path only where it pays; off-TPU the kernel
            # would run interpreted (tests opt in via impl="pallas")
            from ..ops.flash import target_platform

            use_pallas = target_platform() == "tpu"
        else:
            use_pallas = impl == "pallas"

        def build(cfg):
            # cfg: None for the XLA step, else a (tile_rows, fuse) pair
            # for the fused Pallas step
            with_pallas = cfg is not None
            tr, fz = cfg if with_pallas else (0, 1)

            def local(*flat):
                s = SWState(*flat)
                if with_pallas:
                    from . import _sw_pallas

                    shape = s.h.shape
                    # pad to the kernel's aligned block ONCE, outside
                    # the time loop (12 extra copies/step otherwise).
                    # Single-step calls reuse the fused tiling's T so
                    # both kernels agree on the padded shape.
                    T_eff, _, _ = _sw_pallas._tiling(shape[0], tr, fz)
                    s = _sw_pallas.pad_rows(s, tile_rows=tr, fuse=fz)

                    def one_step(st, is_first):
                        return _sw_pallas.fused_step(
                            st, self.params, first=is_first,
                            logical_shape=shape, tile_rows=T_eff,
                            fuse=1,
                        )

                    def fused_steps(st):
                        return _sw_pallas.fused_step(
                            st, self.params, first=False,
                            logical_shape=shape, tile_rows=tr,
                            fuse=fz,
                        )
                else:
                    def one_step(st, is_first):
                        return self._step_local(st, is_first)

                    fused_steps = None

                if first:
                    s = one_step(s, True)
                    remaining = n_steps - 1
                else:
                    remaining = n_steps
                if fused_steps is not None and fz > 1:
                    # temporal blocking: whole fused calls, then the
                    # remainder one step at a time
                    whole, rest = divmod(remaining, fz)
                    if whole > 0:
                        s = lax.fori_loop(
                            0, whole, lambda _, st: fused_steps(st), s)
                    for _ in range(rest):
                        s = one_step(s, False)
                elif remaining > 0:
                    s = lax.fori_loop(
                        0,
                        remaining,
                        lambda _, st: one_step(st, False),
                        s,
                    )
                if with_pallas:
                    s = _sw_pallas.unpad_rows(s, shape)
                return s

            spec = P(*self.grid.axes)
            mapped = jax.shard_map(
                local,
                mesh=self.grid.mesh,
                in_specs=spec,
                out_specs=SWState(*(spec,) * 6),
                check_vma=False,
            )
            return jax.jit(
                lambda state: mapped(*state),
                donate_argnums=(0,) if donate else (),
            )

        if not use_pallas or impl == "pallas":
            # explicit choice (or XLA): no fallback — fail loudly
            return build((tile_rows, fuse) if use_pallas else None)

        # impl="auto" chose pallas: walk a fallback ladder on compile
        # failure — requested config, then a conservative small-window
        # config that sits far below the Mosaic program-size ceiling,
        # then the XLA step.  (An AOT lower+compile probe would be
        # cleaner, but .lower() hangs on tunneled TPU backends, so the
        # first real call is the probe.)  Only *compile-time* failures
        # trigger the fallback — they occur before execution starts, so
        # donated input buffers are still intact for the retry.  Runtime
        # failures re-raise: after donation the inputs may be consumed,
        # and masking the real error with a doomed retry would mislead.
        # Limitation: if `stepper` is traced by an outer jit, the pallas
        # call inlines and a compile failure surfaces at the outer jit's
        # compile — loud, but past this fallback.
        ladder = [(tile_rows, fuse)]
        if (tile_rows, fuse) != (64, 1):
            ladder.append((64, 1))
        ladder.append(None)
        chosen = {"fn": None}
        _COMPILE_MARKERS = (
            "Mosaic", "compile", "Compile", "lowering", "Lowering",
        )

        def stepper(state):
            if chosen["fn"] is not None:
                return chosen["fn"](state)
            last_exc = None
            for i, cfg in enumerate(ladder):
                fn = build(cfg)
                try:
                    out = fn(state)
                    chosen["fn"] = fn
                    return out
                except Exception as exc:
                    msg = f"{type(exc).__name__}: {exc}"
                    is_last = i == len(ladder) - 1
                    if is_last or not any(
                        k in msg for k in _COMPILE_MARKERS
                    ):
                        # a marker-matching *runtime* fault after
                        # donation consumed the inputs: surface the
                        # first compile error as the cause, not mask it
                        raise exc from last_exc
                    import warnings

                    nxt = ladder[i + 1]
                    warnings.warn(
                        f"fused Pallas shallow-water step {cfg} failed "
                        "to compile; falling back to "
                        f"{'XLA' if nxt is None else f'pallas {nxt}'}: "
                        f"{exc}"
                    )
                    last_exc = exc

        return stepper

    def interior(self, field: jax.Array) -> np.ndarray:
        """Reassemble the physical (ny, nx) field from stacked blocks."""
        gy, gx = self.grid.shape
        b = np.asarray(field).reshape(
            gy, self.block_shape[0], gx, self.block_shape[1]
        )
        b = b[:, 1:-1, :, 1:-1]  # (gy, ny_loc, gx, nx_loc)
        return b.reshape(self.ny, self.nx)

    def total_mass(self, state: SWState) -> float:
        return float(np.sum(self.interior(state.h)) * self.params.dx * self.params.dy)
