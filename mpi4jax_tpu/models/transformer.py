"""GPT-style transformer with dp x tp x sp parallelism on one mesh.

The reference ships no models — its parallel-training patterns exist as
test/demo compositions of its primitives (SURVEY.md §2.4: DP grad-allreduce,
TP column-split matvec + allreduce, alltoall transposes, pipeline
send/recv).  This module is those patterns assembled into a complete,
trainable model family, TPU-first:

- **dp**: batch-sharded; gradients synced with allreduce-mean
  (parallel/dp.py).
- **tp**: Megatron-style — attention QKV and MLP up-projections are
  column-parallel, output/down-projections row-parallel with one SUM
  collective each (parallel/tp.py); weights are stored with a leading tp
  axis and sharded over the mesh so each device holds only its block.
- **sp**: sequence-sharded activations with **ring attention**
  (parallel/ring.py) — exact causal attention over the full context with
  one k/v block resident per device.

Everything runs inside one ``shard_map`` over a 3-axis mesh; layers are
stacked and iterated with ``lax.scan`` (one compiled block, TPU-friendly
compile times); matmuls are kept large for the MXU and can run in bfloat16.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .. import ops
from ..parallel.ring import ring_attention


class GPTConfig(NamedTuple):
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 8
    n_layers: int = 2
    d_ff: int = 256
    max_seq: int = 128
    dtype: str = "float32"  # compute dtype; "bfloat16" on real TPU


class GPTParams(NamedTuple):
    # replicated
    wte: jax.Array  # (vocab, d)
    wpe: jax.Array  # (max_seq, d)
    ln1: jax.Array  # (L, 2, d) scale/bias
    ln2: jax.Array  # (L, 2, d)
    lnf: jax.Array  # (2, d)
    b2: jax.Array   # (L, d)  down-proj bias (added post-reduction)
    bo: jax.Array   # (L, d)  attn out bias (added post-reduction)
    # tp-sharded (leading tp axis)
    w_qkv: jax.Array  # (L, tp, d, 3*d/tp)
    w_o: jax.Array    # (L, tp, d/tp, d)
    w1: jax.Array     # (L, tp, d, ff/tp)
    b1: jax.Array     # (L, tp, ff/tp)
    w2: jax.Array     # (L, tp, ff/tp, d)


REPLICATED_FIELDS = ("wte", "wpe", "ln1", "ln2", "lnf", "b2", "bo")
TP_FIELDS = ("w_qkv", "w_o", "w1", "b1", "w2")


def init_params(cfg: GPTConfig, tp: int, seed: int = 0) -> GPTParams:
    if cfg.d_model % cfg.n_heads or cfg.n_heads % tp or cfg.d_ff % tp:
        raise ValueError("d_model/n_heads/d_ff must divide heads and tp")
    rng = np.random.RandomState(seed)
    d, ff, L = cfg.d_model, cfg.d_ff, cfg.n_layers
    s = 0.02

    def norm(*shape):
        return jnp.asarray(rng.randn(*shape).astype(np.float32) * s)

    ln_init = jnp.stack(
        [jnp.ones((L, d), jnp.float32), jnp.zeros((L, d), jnp.float32)],
        axis=1,
    )
    return GPTParams(
        wte=norm(cfg.vocab, d),
        wpe=norm(cfg.max_seq, d),
        ln1=ln_init,
        ln2=ln_init,
        lnf=jnp.stack(
            [jnp.ones((d,), jnp.float32), jnp.zeros((d,), jnp.float32)]
        ),
        b2=jnp.zeros((L, d), jnp.float32),
        bo=jnp.zeros((L, d), jnp.float32),
        w_qkv=norm(L, tp, d, 3 * d // tp),
        w_o=norm(L, tp, d // tp, d),
        w1=norm(L, tp, d, ff // tp),
        b1=jnp.zeros((L, tp, ff // tp), jnp.float32),
        w2=norm(L, tp, ff // tp, d),
    )


def param_specs(tp_axis: str = "tp") -> GPTParams:
    """PartitionSpecs: tp-sharded weights on ``tp_axis``, rest replicated."""
    reps = {f: P() for f in REPLICATED_FIELDS}
    shard = {f: P(None, tp_axis) for f in TP_FIELDS}
    shard["b1"] = P(None, tp_axis)
    return GPTParams(**reps, **shard)


def _layernorm(x, scale_bias):
    scale, bias = scale_bias[0], scale_bias[1]
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + 1e-5) * scale + bias


class GPT:
    """The model, bound to a mesh with ("dp", "tp", "sp") axes."""

    def __init__(self, cfg: GPTConfig, mesh: Mesh,
                 dp_axis="dp", tp_axis="tp", sp_axis="sp"):
        self.cfg = cfg
        self.mesh = mesh
        self.axes = (dp_axis, tp_axis, sp_axis)
        self.tp = mesh.shape[tp_axis]
        self.sp = mesh.shape[sp_axis]
        self.dp = mesh.shape[dp_axis]

    # -- per-rank forward (inside shard_map) ------------------------------
    def _block(self, x, layer, tp_comm):
        """One transformer block on local activations (B_loc, T_loc, d)."""
        cfg = self.cfg
        dp_ax, tp_ax, sp_ax = self.axes
        ln1, ln2, w_qkv, w_o, w1, b1, w2, b2, bo = layer
        dtype = jnp.dtype(cfg.dtype)

        h_loc = cfg.n_heads // self.tp
        hd = cfg.d_model // cfg.n_heads

        # attention: column-parallel qkv (no comm)
        y = _layernorm(x, ln1).astype(dtype)
        qkv = y @ w_qkv.astype(dtype)  # (B, T_loc, 3*d/tp)
        b, t = qkv.shape[0], qkv.shape[1]
        qkv = qkv.reshape(b, t, 3, h_loc, hd)
        q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]
        # exact causal attention over the sequence ring
        attn = ring_attention(q, k, v, axis=sp_ax, causal=True)
        attn = attn.reshape(b, t, h_loc * hd)
        # row-parallel output projection: one SUM collective over tp
        out = ops.allreduce(
            attn @ w_o.astype(dtype), op=ops.SUM, comm=tp_comm
        ) + bo.astype(dtype)
        x = x + out.astype(x.dtype)

        # MLP: column-parallel up, row-parallel down
        y = _layernorm(x, ln2).astype(dtype)
        h = jax.nn.gelu(y @ w1.astype(dtype) + b1.astype(dtype))
        down = ops.allreduce(
            h @ w2.astype(dtype), op=ops.SUM, comm=tp_comm
        ) + b2.astype(dtype)
        return x + down.astype(x.dtype)

    def _forward_local(self, params: GPTParams, tokens):
        """tokens: (B_loc, T_loc) int32 → logits (B_loc, T_loc, vocab)."""
        from ..parallel.mesh import MeshComm

        cfg = self.cfg
        dp_ax, tp_ax, sp_ax = self.axes
        tp_comm = MeshComm(tp_ax, mesh=self.mesh)

        t_loc = tokens.shape[1]
        sp_idx = lax.axis_index(sp_ax)
        pos0 = sp_idx * t_loc

        x = params.wte[tokens] + lax.dynamic_slice(
            params.wpe, (pos0, 0), (t_loc, cfg.d_model)
        )[None]

        # per-layer stacks; [:, 0] squeezes this rank's tp block (the
        # sharded leading tp dim is size 1 per shard)
        stacked = (
            params.ln1, params.ln2,
            params.w_qkv[:, 0], params.w_o[:, 0],
            params.w1[:, 0], params.b1[:, 0], params.w2[:, 0],
            params.b2, params.bo,
        )

        def body(x_, layer):
            return self._block(x_, layer, tp_comm), None

        x, _ = lax.scan(body, x, stacked)
        x = _layernorm(x, params.lnf)
        # tied embeddings.  (Measured r3: casting this projection to the
        # compute dtype per step is a net LOSS on the v5e — the (d,vocab)
        # cast materialization outweighs the matmul savings, 127 ms vs
        # 113 ms per step — so it stays in the residual dtype.)
        return x @ params.wte.T

    def _loss_local(self, params, tokens, targets, mask):
        logits = self._forward_local(params, tokens).astype(jnp.float32)
        logp = jax.nn.log_softmax(logits, axis=-1)
        nll = -jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        nll = nll * mask
        # mean over *global* tokens: sum local, divide by global count
        dp_ax, tp_ax, sp_ax = self.axes
        from ..parallel.mesh import MeshComm

        total = ops.allreduce(
            jnp.sum(nll), op=ops.SUM,
            comm=MeshComm((dp_ax, sp_ax), mesh=self.mesh),
        )
        count = ops.allreduce(
            jnp.sum(mask), op=ops.SUM,
            comm=MeshComm((dp_ax, sp_ax), mesh=self.mesh),
        )
        return total / jnp.maximum(count, 1.0)

    # -- public training step --------------------------------------------
    def train_step_fn(self, example_opt_state, optimizer=None):
        """Build ``step(params, opt_state, tokens) -> (loss, params,
        opt_state)`` jitted over the mesh.

        ``tokens``: (B, T) int32, global. Batch is sharded over dp, the
        sequence over sp, weights over tp.  ``example_opt_state`` (from
        :meth:`init_opt_state`) supplies the optimizer-state structure so
        its param-shaped moments inherit the param shardings.
        """
        import optax

        dp_ax, tp_ax, sp_ax = self.axes
        if optimizer is None:
            optimizer = optax.adamw(3e-4)

        specs = param_specs(tp_ax)
        tok_spec = P(dp_ax, sp_ax)
        # optimizer-state moments are GPTParams subtrees → same shardings
        opt_specs = jax.tree.map(
            lambda x: specs if isinstance(x, GPTParams) else P(),
            example_opt_state,
            is_leaf=lambda x: isinstance(x, GPTParams),
        )

        def local_step(params, opt_state, tokens, targets, mask):
            from ..parallel.mesh import MeshComm

            def loss_fn(p):
                return self._loss_local(p, tokens, targets, mask)

            loss, grads = jax.value_and_grad(loss_fn)(params)

            # gradient sync (see module docstring):
            # - every param: mean over dp and sp replicas
            # - replicated params additionally SUM over tp (each tp rank
            #   holds only its shard's contribution)
            dpsp = MeshComm((dp_ax, sp_ax), mesh=self.mesh)
            tpc = MeshComm(tp_ax, mesh=self.mesh)
            n = dpsp.size()

            def sync(field, g):
                g = ops.allreduce(g, op=ops.SUM, comm=dpsp) / n
                if field in REPLICATED_FIELDS:
                    g = ops.allreduce(g, op=ops.SUM, comm=tpc)
                return g

            grads = GPTParams(
                **{
                    f: sync(f, getattr(grads, f))
                    for f in GPTParams._fields
                }
            )
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return loss[None], params, opt_state

        mapped = jax.shard_map(
            local_step,
            mesh=self.mesh,
            in_specs=(specs, opt_specs, tok_spec, tok_spec, tok_spec),
            out_specs=(P(dp_ax), specs, opt_specs),
            check_vma=False,
        )

        @jax.jit
        def step(params, opt_state, tokens):
            targets = jnp.concatenate(
                [tokens[:, 1:], jnp.zeros_like(tokens[:, :1])], axis=1
            )
            mask = jnp.concatenate(
                [
                    jnp.ones(tokens[:, 1:].shape, jnp.float32),
                    jnp.zeros(tokens[:, :1].shape, jnp.float32),
                ],
                axis=1,
            )
            loss, params2, opt_state2 = mapped(
                params, opt_state, tokens, targets, mask
            )
            return loss[0], params2, opt_state2

        return step

    def init_opt_state(self, params, optimizer=None):
        import optax

        if optimizer is None:
            optimizer = optax.adamw(3e-4)
        return optimizer.init(params)
