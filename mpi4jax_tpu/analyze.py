"""``python -m mpi4jax_tpu.analyze`` — static communication verifier CLI.

Thin entry point; the implementation lives in
:mod:`mpi4jax_tpu.analysis._cli`.
"""

import os
import sys

# the analyzed program never talks to a device; pin cpu before any
# backend initialization so analysis runs identically on every host
os.environ.setdefault("JAX_PLATFORMS", "cpu")

from .analysis import _cli  # noqa: E402

if __name__ == "__main__":
    sys.exit(_cli.main())
