"""ctypes glue to the native transport's observability event ring.

The wire contract lives in ``native/tpucomm.h``: ``TpuObsEvent`` (this
module's :class:`TpuObsEvent` must stay field-for-field identical) and
the ``tpucomm_obs_*`` entry points.  ``wire_bytes`` is each event's
on-wire payload representation — equal to the logical ``bytes`` for
every exact op, the packed int8+scales size for quantized collectives
(qring/qrd), so ``bytes / wire_bytes`` is the compression ratio.
Everything here takes the loaded
library object explicitly — this module never loads (or builds) the
transport itself, so the pure-Python half of the subsystem stays usable
without it.
"""

from __future__ import annotations

import ctypes

#: index-matched names for TpuObsEvent.op (enum TpuObsOp in tpucomm.h)
OBS_OP_NAMES = (
    "Send", "Recv", "Sendrecv", "Shift2", "Barrier", "Bcast", "Gather",
    "Scatter", "Allgather", "Alltoall", "Allreduce", "Reduce", "Scan",
)

#: TpuCollAlgo codes -> names (keep in sync with mpi4jax_tpu/tune)
ALGO_NAMES = {0: "auto", 1: "ring", 2: "rd", 3: "tree", 4: "shm",
              5: "qring", 6: "qrd", 7: "hring", 8: "htree",
              9: "qalltoall", 10: "halltoall", 11: "hqalltoall"}

#: TpuObsTier codes -> names (0 = flat / whole-op, omitted from the
#: canonical events; hierarchical per-leg events carry intra/inter)
TIER_NAMES = {1: "intra", 2: "inter", 3: "ici"}


class TpuObsEvent(ctypes.Structure):
    _fields_ = [
        ("t_start", ctypes.c_double),
        ("dur_s", ctypes.c_double),
        ("wait_s", ctypes.c_double),
        ("queue_s", ctypes.c_double),
        ("nbytes", ctypes.c_int64),
        ("wire_bytes", ctypes.c_int64),
        ("op", ctypes.c_int32),
        ("peer", ctypes.c_int32),
        ("tag", ctypes.c_int32),
        ("algo", ctypes.c_int32),
        ("tier", ctypes.c_int32),
        # transport syscalls issued while the op executed (the uring
        # generation's submit-batching attribution); occupies the former
        # padding slot, so the layout is unchanged — but a pre-uring .so
        # never writes it, which is why drain() gates the field on
        # syscalls_available()
        ("syscalls", ctypes.c_int32),
        # link-layer recovery events absorbed while the op executed
        # (self-healing generation: retries + reconnects the op rode
        # through transparently); widened the struct 72 -> 80 bytes, so
        # available() requires tpucomm_link_counters as the layout probe
        ("retries", ctypes.c_int32),
        ("reserved0", ctypes.c_int32),
    ]

#: process-total link-layer counter names, index-matched to the
#: ``tpucomm_link_counters`` out-params (native/tpucomm.h)
LINK_COUNTER_NAMES = ("retries", "reconnects", "dup_dropped",
                     "crc_errors", "replayed", "heartbeats")


#: bytes per ring slot, for sizing the ring from MPI4JAX_TPU_TRACE_BUF_KB
EVENT_BYTES = ctypes.sizeof(TpuObsEvent)


def available(lib) -> bool:
    """True when the loaded .so carries the event ring (a stale prebuilt
    library predating it keeps working, just unobserved).

    ``tpucomm_set_topology`` doubles as the layout probe: a library
    from before the topology subsystem records events WITHOUT the
    ``tier`` field (pre-quantization ones also lack ``wire_bytes``,
    pre-progress-engine ones ``queue_s``), which this module would
    misparse — such a library is treated as unobserved rather than
    decoded wrong.  ``tpucomm_link_counters`` is the probe for the
    self-healing generation, whose events grew ``retries`` (72 -> 80
    byte slots — an older library's ring would be misparsed too)."""
    if lib is None or not hasattr(lib, "tpucomm_obs_enable"):
        return False
    if not hasattr(lib, "tpucomm_execute"):
        return False
    if not hasattr(lib, "tpucomm_quant_packed_bytes"):
        return False
    if not hasattr(lib, "tpucomm_set_topology"):
        return False
    if not hasattr(lib, "tpucomm_link_counters"):
        return False
    # idempotent signature setup (works for bridge-loaded and
    # standalone-loaded libraries alike)
    lib.tpucomm_obs_enable.argtypes = [ctypes.c_int, ctypes.c_int64]
    lib.tpucomm_obs_enable.restype = None
    lib.tpucomm_obs_counts.argtypes = [
        ctypes.POINTER(ctypes.c_int64), ctypes.POINTER(ctypes.c_int64)]
    lib.tpucomm_obs_counts.restype = None
    lib.tpucomm_obs_drain.restype = ctypes.c_int64
    lib.tpucomm_obs_clock.restype = ctypes.c_double
    return True


def syscalls_available(lib) -> bool:
    """True when the loaded .so writes ``TpuObsEvent.syscalls`` —
    ``tpucomm_uring_status`` is the layout probe for the uring
    generation.  A pre-uring library's slot is always 0 (the former
    padding), and reporting a fake 0 as a measurement would poison the
    syscalls-per-message benchmarks, so the field is omitted instead."""
    return lib is not None and hasattr(lib, "tpucomm_uring_status")


def enable(lib, capacity_events: int) -> None:
    lib.tpucomm_obs_enable(1, ctypes.c_int64(int(capacity_events)))


def disable(lib) -> None:
    lib.tpucomm_obs_enable(0, ctypes.c_int64(0))


def counts(lib):
    """(events held, events dropped by overflow) right now."""
    rec = ctypes.c_int64(0)
    drop = ctypes.c_int64(0)
    lib.tpucomm_obs_counts(ctypes.byref(rec), ctypes.byref(drop))
    return rec.value, drop.value


def clock(lib) -> float:
    """The native recorder clock (monotonic seconds, process epoch)."""
    fn = lib.tpucomm_obs_clock
    fn.restype = ctypes.c_double
    return float(fn())


def _decode(buf, got, syscalls_ok):
    """Struct slots -> raw event dicts (shared by drain and peek)."""
    out = []
    for i in range(got):
        e = buf[i]
        op = OBS_OP_NAMES[e.op] if 0 <= e.op < len(OBS_OP_NAMES) else "?"
        ev = {
            "name": op,
            "t": e.t_start,
            "dur_s": e.dur_s,
            "wait_s": e.wait_s,
            "queue_s": e.queue_s,
            "bytes": e.nbytes,
            "wire_bytes": e.wire_bytes,
            "peer": e.peer,
            "tag": e.tag,
            "algo": ALGO_NAMES.get(e.algo),
            "tier": TIER_NAMES.get(e.tier),
        }
        if syscalls_ok:
            # only a uring-generation library writes the field; a
            # pre-uring .so's slot is stale padding, never a count
            ev["syscalls"] = e.syscalls
        if e.retries:
            # link-layer recovery events this op rode through; carried
            # only when nonzero — fault-free recordings (the vast
            # majority) stay schema-identical
            ev["retries"] = e.retries
        out.append(ev)
    return out


def drain(lib, max_events: int = 1 << 20):
    """Pull and clear the held events, oldest first, as raw dicts with
    the native clock's timestamps (seconds): op/peer/tag/bytes/algo/
    t/dur_s/wait_s/queue_s (the dispatch phase: post -> native start,
    0 for inline execution).  Events the buffer cannot take (appended
    between the count probe and the drain, or beyond ``max_events``)
    are counted as dropped by the native side, never silently lost."""
    held, _ = counts(lib)
    # headroom for events appended after the count probe (the native
    # drain clamps to what is actually held)
    n = min(held + 64, max_events)
    if n <= 0 or held <= 0:
        return []
    buf = (TpuObsEvent * n)()
    got = lib.tpucomm_obs_drain(buf, ctypes.c_int64(n))
    return _decode(buf, got, syscalls_available(lib))


def peek_available(lib) -> bool:
    """True when the loaded .so carries the non-destructive cursor read
    (``tpucomm_obs_peek``) — the live controller's follow path.  A
    library predating it still records and drains; only the second
    consumer is unavailable."""
    return available(lib) and hasattr(lib, "tpucomm_obs_peek")


def peek(lib, cursor: int, max_events: int = 4096):
    """Non-destructive follow of the native ring from an absolute
    per-enable sequence ``cursor`` (0 = the oldest held event).
    Returns ``(events, next_cursor, skipped)`` — the same raw dicts as
    :func:`drain`, the cursor to resume from, and how many events
    between ``cursor`` and the oldest still readable were lost to ring
    overflow or a destructive drain.  Never touches the held/dropped
    counts, so the end-of-run :func:`drain` still sees every held
    event (the two-consumer contract the live controller relies on)."""
    n = max(int(max_events), 1)
    buf = (TpuObsEvent * n)()
    cur = ctypes.c_int64(int(cursor))
    skipped = ctypes.c_int64(0)
    lib.tpucomm_obs_peek.restype = ctypes.c_int64
    got = lib.tpucomm_obs_peek(buf, ctypes.c_int64(n), ctypes.byref(cur),
                               ctypes.byref(skipped))
    return (_decode(buf, got, syscalls_available(lib)), cur.value,
            skipped.value)


def link_counters(lib):
    """Process-total self-healing counters as a dict (see
    ``LINK_COUNTER_NAMES``), or ``None`` when the loaded library
    predates the link layer.  All-zero on every fault-free run — and
    with ``MPI4JAX_TPU_RETRY`` unset the layer never arms, so the
    counters stay zero by construction."""
    if lib is None or not hasattr(lib, "tpucomm_link_counters"):
        return None
    vals = [ctypes.c_int64(0) for _ in LINK_COUNTER_NAMES]
    lib.tpucomm_link_counters(*[ctypes.byref(v) for v in vals])
    return {name: int(v.value)
            for name, v in zip(LINK_COUNTER_NAMES, vals)}
