"""Aggregation over recorded events, and the shared benchmark serializer.

Pure stdlib: the launcher's merge step and the profile CLI run this
without jax, numpy, or the native library.

Canonical event shape (every producer — the native ring, the ops-layer
``CallTrace`` hook, and part-file loads — normalizes to this):

    {"name": "Allreduce", "src": "native" | "ops", "ts_us": float,
     "dur_us": float, "wait_us": float, "dispatch_us": float,
     "bytes": int, "peer": int, "tag": int,
     "algo": "ring" | ... | None}

plus an optional ``wire_bytes`` carried ONLY when it differs from
``bytes`` (quantized collectives: the packed int8+scales payload), and
an optional ``tier`` (``"intra"`` / ``"inter"`` on the native
hierarchical legs; ``"ici"`` on the Pallas ICI intra leg's ops-src
span) carried ONLY on a hierarchical collective's per-leg events — the
whole-op record stays tier-less, so per-leg rows never double-count
against it and pre-topology recordings stay schema-compatible.

``dispatch_us`` is the submission-queue delay of an engine-queued op
(post -> native execution start; 0 for inline execution) — the host
dispatch share, separated from the peer-wait share (``wait_us``) and
the wire share (``dur - dispatch - wait``).

``ts_us`` is on the job-global aligned timeline (unix microseconds plus
the rank's estimated clock offset — see ``_trace.py``).
"""

from __future__ import annotations

import math

try:
    from ..utils import config as _config
except ImportError:  # pragma: no cover - standalone tooling load
    import importlib.util as _ilu
    import os as _os

    _spec = _ilu.spec_from_file_location(
        "m4j_stats_config",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      _os.pardir, "utils", "config.py"),
    )
    _config = _ilu.module_from_spec(_spec)
    _spec.loader.exec_module(_config)

STATS_SCHEMA = "mpi4jax_tpu.obs.stats/1"


def _sig(x: float, figures: int = 4) -> float:
    """Round to significant figures: throughputs span nine orders of
    magnitude across benchmark points, so fixed decimals would collapse
    the small end to 0."""
    return float(f"{float(x):.{figures}g}")


def percentile(values, q: float) -> float:
    """``numpy.percentile(values, q)`` (the default linear-interpolation
    method), reimplemented so the stdlib-only paths agree bit-for-bit
    with numpy on the same corpus (test-enforced)."""
    vals = sorted(float(v) for v in values)
    if not vals:
        return 0.0
    if len(vals) == 1:
        return vals[0]
    k = (len(vals) - 1) * (float(q) / 100.0)
    f = math.floor(k)
    c = math.ceil(k)
    if f == c:
        return vals[int(k)]
    return vals[f] * (c - k) + vals[c] * (k - f)


def summarize(events, dropped=None, rank=None, link=None) -> dict:
    """Per-(op, source, peer, algorithm) aggregates over canonical
    events.

    Returns ``{"schema", "rank", "total_events", "dropped", "per_op"}``
    where ``per_op`` rows carry count, total bytes, p50/p95/p99 latency
    (microseconds), the dispatch fraction (share of wall time spent in
    the engine's submission queue — host dispatch, not communication),
    the wait fraction (share blocked on peers rather than moving
    bytes), and effective GB/s (``sum(bytes) / sum(seconds)`` —
    payload over wall time, no algorithm factor).

    ``link`` (the transport's process-total self-healing counters,
    see ``obs._recorder.link_counters``) adds a top-level
    ``self_healing`` dict when any counter is nonzero — fault-free
    stats stay schema-identical.
    """
    groups = {}
    tier_bytes = {}
    for ev in events:
        # src is part of the key: the native ring and the ops-layer
        # span record the SAME call from two vantage points — collapsing
        # them would double-count every send/recv and dilute wait_frac.
        # tier is part of the key too: a hierarchical collective's
        # intra/inter leg events must not merge with (or into) the
        # whole-op record.  phase likewise: a serving span labeled
        # prefill must not pool its latencies with decode or kv_xfer
        # ones — per-phase percentiles are the SLO loop's signal.
        key = (ev.get("name", "?"), ev.get("src", "?"),
               int(ev.get("peer", -1)), ev.get("algo") or "-",
               ev.get("tier") or "-", ev.get("phase") or "-")
        groups.setdefault(key, []).append(ev)
        if ev.get("tier"):
            tier_bytes[ev["tier"]] = (tier_bytes.get(ev["tier"], 0)
                                      + int(ev.get("bytes", 0)))
    rows = []
    for (op, src, peer, algo, tier, phase), evs in sorted(groups.items()):
        durs = [float(e.get("dur_us", 0.0)) for e in evs]
        waits = [float(e.get("wait_us", 0.0)) for e in evs]
        disps = [float(e.get("dispatch_us", 0.0)) for e in evs]
        nbytes = sum(int(e.get("bytes", 0)) for e in evs)
        wire_bytes = sum(int(e.get("wire_bytes", e.get("bytes", 0)))
                         for e in evs)
        seconds = sum(durs) / 1e6
        row = {
            "op": op,
            "src": src,
            "peer": peer,
            "algo": algo,
            "count": len(evs),
            "bytes": nbytes,
            "seconds": round(seconds, 9),
            "p50_us": round(percentile(durs, 50), 3),
            "p95_us": round(percentile(durs, 95), 3),
            "p99_us": round(percentile(durs, 99), 3),
            "dispatch_frac": round(sum(disps) / max(sum(durs), 1e-12), 4),
            "wait_frac": round(sum(waits) / max(sum(durs), 1e-12), 4),
            "eff_GBps": _sig(nbytes / max(seconds, 1e-12) / 1e9),
        }
        if tier != "-":
            # hierarchical per-leg row: name the transport tier it
            # moved on (exact rows stay schema-identical)
            row["tier"] = tier
        if phase != "-":
            # serving-plane row: prefill / decode / kv_xfer — present
            # only on labeled spans, so non-serving stats are unchanged
            row["phase"] = phase
        if wire_bytes != nbytes:
            # quantized wire formats: logical vs on-wire payload.  The
            # column appears only when it says something (exact rows
            # stay schema-identical to pre-quantization stats), and
            # eff_GBps above stays LOGICAL bytes over wall time — the
            # number comparable across compressed and exact runs.
            row["wire_bytes"] = wire_bytes
            row["compression"] = _sig(nbytes / max(wire_bytes, 1))
        if any("syscalls" in e for e in evs):
            # transport syscalls (uring-generation recordings only):
            # total + per-op mean, the submit-batching attribution —
            # pre-uring recordings stay schema-identical
            total_sys = sum(int(e.get("syscalls", 0)) for e in evs)
            row["syscalls"] = total_sys
            row["syscalls_per_op"] = _sig(total_sys / max(len(evs), 1))
        if any(e.get("retries") for e in evs):
            # self-healing recoveries these ops rode through (retry +
            # reconnect events absorbed transparently); the column
            # appears only when a fault actually landed, so fault-free
            # recordings stay schema-identical
            row["retries"] = sum(int(e.get("retries", 0)) for e in evs)
        rows.append(row)
    out = {
        "schema": STATS_SCHEMA,
        "total_events": len(events),
        "dropped": dict(dropped or {}),
        "per_op": rows,
    }
    if tier_bytes:
        # intra- vs inter-island byte split of the hierarchical
        # collectives (per-leg events only — whole-op records carry no
        # tier, so nothing is counted twice)
        out["tier_bytes"] = {k: int(v)
                             for k, v in sorted(tier_bytes.items())}
    if link and any(int(v) for v in link.values()):
        # process-total self-healing counters (cumulative, not ring
        # entries: they survive overflow).  Present only when the link
        # layer actually recovered something — retries/reconnects/
        # dup_dropped/crc_errors/replayed/heartbeats, the diag
        # self_healing check's assertion surface
        out["self_healing"] = {k: int(v) for k, v in sorted(link.items())}
    if rank is not None:
        out["rank"] = int(rank)
    return out


def render_table(stats: dict, *, by=("op", "algo")) -> str:
    """Human-readable per-op table (the profile CLI's ``report``)."""
    cols = ("op", "src", "peer", "algo", "count", "bytes", "p50_us",
            "p95_us", "p99_us", "dispatch_frac", "wait_frac", "eff_GBps")
    rows = stats.get("per_op", [])
    if any("tier" in r for r in rows):
        # hierarchical per-leg rows present: show the transport tier
        # (flat rows render blank)
        cols = cols + ("tier",)
    if any("phase" in r for r in rows):
        # serving-plane rows present: show the phase split
        # (non-serving rows render blank)
        cols = cols + ("phase",)
    if any("compression" in r for r in rows):
        # quantized rows present: show the on-wire compression ratio
        # (exact rows render blank — their wire IS the logical payload)
        cols = cols + ("compression",)
    if any("syscalls_per_op" in r for r in rows):
        # uring-generation rows: syscalls per op (submit batching)
        cols = cols + ("syscalls_per_op",)
    if any("retries" in r for r in rows):
        # self-healing rows present: show absorbed recoveries
        # (fault-free rows render blank)
        cols = cols + ("retries",)
    if not rows:
        return "(no events recorded)"
    widths = {c: max(len(c), *(len(str(r.get(c, ""))) for r in rows))
              for c in cols}
    lines = ["  ".join(c.ljust(widths[c]) for c in cols)]
    lines.append("  ".join("-" * widths[c] for c in cols))
    for r in rows:
        lines.append("  ".join(str(r.get(c, "")).ljust(widths[c])
                               for c in cols))
    dropped = stats.get("dropped") or {}
    total_drop = sum(int(v) for v in dropped.values())
    lines.append(
        f"{stats.get('total_events', len(rows))} events"
        + (f", {total_drop} dropped on ring overflow" if total_drop else "")
    )
    return "\n".join(lines)


def bench_record(*, op, nbytes, seconds, ranks=None, tier=None, algo=None,
                 reps=None, **extra) -> dict:
    """The one benchmark-output serializer: ``benchmarks/*.py``,
    ``obs.stats`` rows, and the profile report all speak these field
    names, so BENCH_*.json artifacts, sweep curves, and recorded-run
    reports stay join-able on (op, bytes, seconds).

    ``eff_GBps_per_chip`` uses the ring-effective convention the BENCH
    artifacts established (``2*(n-1)/n * bytes / seconds`` per rank)
    when ``ranks`` is given, falling back to plain payload-over-time.

    Every row is stamped with the active knob environment
    (``config.knob_env()``: the resolved COLL_ALGO/COLL_QUANT/HIER/
    URING/PLAN gates) so a committed BENCH artifact is reproducible
    without reading the shell history; pass ``knobs=...`` in ``extra``
    to override (the ``--knob-grid`` sweep stamps the combination it
    forced on the sub-job).
    """
    seconds = float(seconds)
    try:
        knobs = _config.knob_env()
    except ValueError as e:
        # a malformed gate aborts loudly wherever it MATTERS (the
        # native parser exits on it); a mesh-tier benchmark that never
        # touches those gates must not crash on the stamp — record the
        # problem instead of fabricating a resolution
        knobs = {"unparseable": str(e)}
    rec = {
        "op": str(op),
        "bytes": int(nbytes),
        "seconds": round(seconds, 9),
        "us": round(seconds * 1e6, 3),
        "knobs": knobs,
    }
    if ranks is not None:
        n = max(int(ranks), 1)
        factor = 2 * (n - 1) / n if n > 1 else 1.0
        rec["ranks"] = n
        rec["eff_GBps_per_chip"] = _sig(
            factor * int(nbytes) / max(seconds, 1e-12) / 1e9)
    else:
        rec["eff_GBps_per_chip"] = _sig(
            int(nbytes) / max(seconds, 1e-12) / 1e9)
    if tier is not None:
        rec["tier"] = str(tier)
    if algo is not None:
        rec["algo"] = str(algo)
    if reps is not None:
        rec["reps"] = int(reps)
    rec.update(extra)
    return rec
