"""Chrome-trace-event export and the cross-rank merge.

Pure stdlib.  Output is the Trace Event Format's JSON-object form
(``{"traceEvents": [...]}``) with complete ("X") events, which Perfetto
and chrome://tracing both load:

- ``pid`` = rank (with a ``process_name`` metadata event per rank),
- ``tid`` 0 = the native transport, ``tid`` 1 = the ops layer,
- every op span carries ``args`` with bytes / peer / tag / algorithm /
  the exact ``wait_us`` and ``dispatch_us``,
- each native span additionally gets nested child slices ``dispatch``
  (submission-queue delay of an engine-queued op), ``wait``, and
  ``wire``, rendering the host-dispatch/blocked/transfer split
  visually (dispatch and wait are drawn as the span's prefix — an
  approximation of their true distribution inside the op;
  ``args.dispatch_us`` / ``args.wait_us`` are exact).

Timestamps are microseconds on the job-global aligned timeline: each
rank's dump already applied its clock offset (estimated over the
freshly-bootstrapped transport mesh — see ``runtime/bridge.py``), so
the merge is a concatenation plus metadata, and cross-rank ordering of
matched sends/recvs survives the ranks' unsynchronized monotonic
clocks.
"""

from __future__ import annotations

TRACE_SCHEMA = "mpi4jax_tpu.obs.trace/1"

_TID_NAMES = {0: "transport (native)", 1: "ops layer (python)"}


def rank_trace_events(events, rank: int):
    """Chrome 'X' events (plus thread metadata) for one rank's canonical
    event list."""
    out = []
    for tid, name in _TID_NAMES.items():
        out.append({"name": "thread_name", "ph": "M", "pid": int(rank),
                    "tid": tid, "args": {"name": name}})
    out.append({"name": "process_name", "ph": "M", "pid": int(rank),
                "tid": 0, "args": {"name": f"rank {rank}"}})
    for ev in events:
        tid = 0 if ev.get("src") == "native" else 1
        ts = float(ev["ts_us"])
        dur = max(float(ev.get("dur_us", 0.0)), 0.001)
        args = {
            "bytes": int(ev.get("bytes", 0)),
            "peer": int(ev.get("peer", -1)),
            "tag": int(ev.get("tag", 0)),
            "wait_us": round(float(ev.get("wait_us", 0.0)), 3),
            "dispatch_us": round(float(ev.get("dispatch_us", 0.0)), 3),
        }
        if ev.get("algo"):
            args["algo"] = ev["algo"]
        if ev.get("tier"):
            args["tier"] = ev["tier"]  # hierarchical leg: intra / inter
        if ev.get("phase"):
            args["phase"] = ev["phase"]  # serving: prefill/decode/kv_xfer
        if "syscalls" in ev:
            # transport syscalls of this op (uring-generation events):
            # the submit-batching win, visible per span in Perfetto
            args["syscalls"] = int(ev["syscalls"])
        wb = int(ev.get("wire_bytes", ev.get("bytes", 0)))
        if wb != args["bytes"]:
            args["wire_bytes"] = wb  # quantized: compressed payload
        out.append({"name": ev.get("name", "?"), "cat": ev.get("src", "?"),
                    "ph": "X", "pid": int(rank), "tid": tid,
                    "ts": round(ts, 3), "dur": round(dur, 3), "args": args})
        wait = float(ev.get("wait_us", 0.0))
        disp = float(ev.get("dispatch_us", 0.0))
        if tid == 0 and (wait > 0.0 or disp > 0.0):
            # nested child slices: dispatch prefix (submission-queue
            # delay), then wait, then the wire phase
            disp = min(max(disp, 0.0), dur)
            wait = min(max(wait, 0.0), dur - disp)
            off = 0.0
            if disp > 0.0:
                out.append({"name": "dispatch", "cat": "phase", "ph": "X",
                            "pid": int(rank), "tid": tid, "ts": round(ts, 3),
                            "dur": round(disp, 3), "args": {}})
                off += disp
            if wait > 0.0:
                out.append({"name": "wait", "cat": "phase", "ph": "X",
                            "pid": int(rank), "tid": tid,
                            "ts": round(ts + off, 3),
                            "dur": round(wait, 3), "args": {}})
                off += wait
            if dur - off > 0.0:
                out.append({"name": "wire", "cat": "phase", "ph": "X",
                            "pid": int(rank), "tid": tid,
                            "ts": round(ts + off, 3),
                            "dur": round(dur - off, 3), "args": {}})
    return out


def merge_parts(parts) -> dict:
    """One Perfetto-loadable trace from per-rank part dicts (the files
    ranks dump at finalize — see ``_dump.py``).  Parts may arrive in any
    order; events are globally time-sorted."""
    trace_events = []
    world_size = 0
    dropped = {}
    generations = {}
    for part in parts:
        rank = int(part.get("rank", 0))
        world_size = max(world_size, int(part.get("size", rank + 1)))
        # elastic worlds: each part says which generation its rank
        # ended in; a merged timeline spanning a recovery shows it here
        generations[f"rank{rank}"] = int(part.get("generation", 0))
        for src, n in (part.get("dropped") or {}).items():
            dropped[f"rank{rank}.{src}"] = int(n)
        trace_events.extend(rank_trace_events(part.get("events", ()), rank))
    meta = [e for e in trace_events if e.get("ph") == "M"]
    spans = sorted((e for e in trace_events if e.get("ph") != "M"),
                   key=lambda e: (e.get("ts", 0.0), e.get("pid", 0)))
    other = {
        "schema": TRACE_SCHEMA,
        "tool": "mpi4jax_tpu.obs",
        "world_size": world_size,
        "dropped": dropped,
    }
    if any(generations.values()):
        other["generations"] = generations
    return {
        "traceEvents": meta + spans,
        "displayTimeUnit": "ms",
        "otherData": other,
    }


def validate_chrome_trace(trace) -> list:
    """Errors (empty = valid) against the Chrome trace-event JSON-object
    schema subset this exporter emits; used by the diag ``observability``
    check and the tests."""
    errors = []
    if not isinstance(trace, dict):
        return ["top level must be a JSON object"]
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["missing traceEvents list"]
    for i, ev in enumerate(events):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not an object")
            continue
        if not isinstance(ev.get("name"), str) or not ev.get("name"):
            errors.append(f"{where}: missing name")
        ph = ev.get("ph")
        if ph not in ("X", "B", "E", "M", "i", "C"):
            errors.append(f"{where}: bad ph {ph!r}")
            continue
        if not isinstance(ev.get("pid"), int):
            errors.append(f"{where}: pid must be an int")
        if ph == "X":
            for field in ("ts", "dur"):
                v = ev.get(field)
                if not isinstance(v, (int, float)):
                    errors.append(f"{where}: {field} must be a number")
            if isinstance(ev.get("dur"), (int, float)) and ev["dur"] < 0:
                errors.append(f"{where}: negative dur")
    return errors
