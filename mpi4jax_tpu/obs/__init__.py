"""Observability: structured per-op event recording, stats, and traces.

The reference's sole observability is per-call debug prints
(``mpi_xla_bridge.pyx`` there, ``utils/tracing.py`` +
``native/tpucomm.cc`` debug lines here).  This package replaces
grep-able stderr with structured telemetry:

- a **per-rank event recorder** — a fixed-size in-memory ring on the
  native side (world-tier transport ops: op, peer/root, tag, bytes,
  algorithm, wait/transfer split) plus an ops-layer span ring fed by
  ``tracing.CallTrace`` — with exact drop accounting on overflow and
  strictly zero cost when disabled;
- :func:`stats` — per-op / per-peer / per-algorithm aggregates (count,
  bytes, p50/p95/p99 latency, wait fraction, effective GB/s);
- **Chrome-trace export** — ``mpi4jax_tpu.launch --trace out.json``
  merges every rank's recording (clock-offset aligned) into one
  Perfetto-loadable timeline; ``python -m mpi4jax_tpu.profile``
  renders tables from the same dumps;
- a **feedback path into the tuner** — ``python -m mpi4jax_tpu.tune
  --from-trace`` derives the persistent algorithm cache from recorded
  real-run timings instead of a synthetic sweep.

Recording turns on via ``MPI4JAX_TPU_TRACE=<out-path>`` (the launcher's
``--trace`` sets it) or programmatically via :func:`start`; ring size is
``MPI4JAX_TPU_TRACE_BUF_KB`` (utils/config.py is the registry).  This
package is stdlib-importable without jax, numpy, or the native library —
the launcher's merge step and the profile CLI rely on that.
"""

from ._dump import (  # noqa: F401
    load_events,
    load_events_meta,
    load_part,
    part_path,
    part_paths,
    write_part,
)
from ._recorder import (  # noqa: F401
    Recorder,
    clock_offset_us,
    default_capacity_events,
    dropped,
    enabled,
    events,
    generation,
    link_counters,
    record_span,
    reset,
    start,
    stop,
)
from ._stats import (  # noqa: F401
    STATS_SCHEMA,
    bench_record,
    percentile,
    render_table,
    summarize,
)
from ._trace import (  # noqa: F401
    TRACE_SCHEMA,
    merge_parts,
    rank_trace_events,
    validate_chrome_trace,
)
from . import _recorder


def stats(event_list=None) -> dict:
    """Aggregates over ``event_list`` (default: everything this rank has
    recorded so far) — see ``_stats.summarize`` for the row schema."""
    if event_list is None:
        event_list = events()
        return summarize(event_list, dropped=dropped(),
                         rank=_recorder.rank(),
                         link=_recorder.link_counters())
    return summarize(event_list)


def dump(base_path: str) -> str:
    """Write this rank's recording part file (``<base>.rank<r>.json``);
    returns the path.  Called automatically at interpreter exit when
    ``MPI4JAX_TPU_TRACE`` is set (see ``runtime/bridge.py``)."""
    return write_part(
        base_path,
        rank=_recorder.rank(),
        size=_recorder.size(),
        events=events(),
        dropped=dropped(),
        clock_offset_us=clock_offset_us(),
        generation=_recorder.generation(),
    )


def merge_files(part_files) -> dict:
    """Merged Chrome trace dict from part file paths."""
    return merge_parts([load_part(p) for p in part_files])
