"""Per-rank event recorder: a fixed-size ring with drop accounting.

Two sources feed it:

- the **native ring** in the transport (``native/tpucomm.cc``), drained
  lazily through ``_native.drain`` — world-tier wire ops with the
  wait/transfer split measured inside the transport itself;
- **ops-layer spans** pushed by ``utils/tracing.py``'s ``CallTrace``
  hook (:func:`record_span`) — the host-side view of the same calls,
  including marshalling/callback overhead the native timing excludes.

Disabled (the default) costs one module-global bool check per call on
the Python side and one relaxed atomic load in the native transport; no
clocks are read and no ring slot is written anywhere (test-enforced).
"""

from __future__ import annotations

import threading
import time

try:
    from ..utils import config
except ImportError:  # pragma: no cover - standalone tooling load
    import importlib.util
    import os as _os

    _spec = importlib.util.spec_from_file_location(
        "m4j_obs_config",
        _os.path.join(_os.path.dirname(_os.path.abspath(__file__)),
                      _os.pardir, "utils", "config.py"),
    )
    config = importlib.util.module_from_spec(_spec)
    _spec.loader.exec_module(config)

from . import _native

#: the ONLY thing a disabled hot path reads (module global, no lock)
_ENABLED = False


class Recorder:
    """Fixed-capacity event ring: overflow overwrites the oldest entry
    and counts it, so a snapshot always reports exactly what is missing
    (the Python twin of the native ring's contract)."""

    def __init__(self, capacity: int):
        self.capacity = max(int(capacity), 16)
        self._buf = [None] * self.capacity
        self._total = 0
        self._lock = threading.Lock()

    def append(self, event: dict) -> None:
        with self._lock:
            self._buf[self._total % self.capacity] = event
            self._total += 1

    def extend(self, events) -> None:
        with self._lock:
            for event in events:
                self._buf[self._total % self.capacity] = event
                self._total += 1

    @property
    def dropped(self) -> int:
        return max(0, self._total - self.capacity)

    def snapshot(self):
        """Held events, oldest first (does not clear)."""
        with self._lock:
            held = min(self._total, self.capacity)
            first = self._total - held
            return [self._buf[(first + i) % self.capacity]
                    for i in range(held)]

    def clear(self) -> None:
        with self._lock:
            self._buf = [None] * self.capacity
            self._total = 0


class _State:
    lib = None            # native library (None = python spans only)
    rank = 0
    size = 1
    generation = 0        # elastic world generation (0 = original world)
    clock_offset_us = 0.0  # cross-rank alignment shift for this rank
    steady0 = 0.0          # native clock sample ...
    unix0 = 0.0            # ... taken at this unix time
    spans: Recorder = None       # ops-layer spans
    native_acc: Recorder = None  # drained native events (canonical form)
    native_dropped = 0           # native overflow total at last pull


_state = _State()


def enabled() -> bool:
    return _ENABLED


def default_capacity_events() -> int:
    """Ring capacity from ``MPI4JAX_TPU_TRACE_BUF_KB`` (default 256 KB
    of 80-byte native slots = 3276 events; same count on the Python
    side)."""
    raw = config.setting("MPI4JAX_TPU_TRACE_BUF_KB", "256")
    try:
        kb = float(raw)
    except ValueError:
        raise ValueError(
            f"cannot parse MPI4JAX_TPU_TRACE_BUF_KB={raw!r} as KB")
    if kb <= 0:
        kb = 256.0
    return max(16, int(kb * 1024) // _native.EVENT_BYTES)


def start(lib=None, capacity_events=None, rank=0, size=1,
          clock_offset_s=0.0, generation=None) -> None:
    """Arm recording.  ``lib`` (the loaded transport) is optional — the
    Python span recorder works alone for mesh-tier / single-process use.
    ``clock_offset_s`` shifts this rank's timestamps onto the job-global
    timeline (see ``runtime/bridge.py``'s alignment handshake).
    ``generation`` stamps the recording with the elastic world
    generation (default: the live generation — elastic recovery mirrors
    it into MPI4JAX_TPU_GENERATION, and the re-arm after a rebuild runs
    through here, so post-recovery events carry the new generation)."""
    global _ENABLED
    cap = capacity_events or default_capacity_events()
    _state.lib = lib if _native.available(lib) else None
    _state.rank = int(rank)
    _state.size = int(size)
    if generation is None:
        generation = config.generation()
    _state.generation = int(generation)
    _state.clock_offset_us = float(clock_offset_s) * 1e6
    _state.spans = Recorder(cap)
    _state.native_acc = Recorder(cap)
    _state.native_dropped = 0
    if _state.lib is not None:
        # map the native monotonic clock to the unix epoch: take the
        # sample pair with the tightest bracket (least scheduling noise)
        best = None
        for _ in range(5):
            u0 = time.time()
            s = _native.clock(_state.lib)
            u1 = time.time()
            if best is None or (u1 - u0) < best[0]:
                best = (u1 - u0, s, (u0 + u1) / 2)
        _state.steady0 = best[1]
        _state.unix0 = best[2]
        _native.enable(_state.lib, cap)
    _ENABLED = True


def stop() -> None:
    global _ENABLED
    _ENABLED = False
    if _state.lib is not None:
        _native.disable(_state.lib)


def reset() -> None:
    """Drop everything recorded so far (stays armed)."""
    if _state.spans is not None:
        _state.spans.clear()
        _state.native_acc.clear()
    _state.native_dropped = 0
    if _state.lib is not None:
        _native.enable(_state.lib, _state.spans.capacity)


def record_span(name: str, t_unix: float, dur_s: float, *, peer=-1,
                nbytes=0, tag=0, algo=None, tier=None,
                phase=None) -> None:
    """Ops-layer span hook (called by ``tracing.CallTrace`` only when
    :func:`enabled` — callers guard, so the disabled path never reaches
    here).  ``tier`` marks a per-leg event (e.g. the Pallas ICI intra
    leg's ``tier="ici"``) nested inside a whole-op record: stats then
    attributes the leg's bytes in ``tier_bytes`` while the tuner keeps
    ignoring tier-carrying events (``_usable_trace_event``), exactly as
    it does for the native hierarchical leg events.  ``phase`` labels a
    serving-plane span (``prefill`` / ``decode`` / ``kv_xfer``) so
    stats and the load generator split percentiles per phase; absent
    on every non-serving span, so pre-serving recordings stay
    schema-identical."""
    if _state.spans is None:
        return
    ev = {
        "name": name,
        "src": "ops",
        "ts_us": t_unix * 1e6 + _state.clock_offset_us,
        "dur_us": dur_s * 1e6,
        "wait_us": 0.0,
        "dispatch_us": 0.0,
        "bytes": int(nbytes),
        "peer": int(peer),
        "tag": int(tag),
        "algo": algo,
    }
    if tier:
        ev["tier"] = str(tier)
    if phase:
        ev["phase"] = str(phase)
    _state.spans.append(ev)


def canonicalize_native(raw, to_unix: float = 0.0,
                        clock_offset_us: float = 0.0):
    """Raw native drain/peek dicts -> canonical events (the dump/stats
    schema).  Shared by the recorder's destructive drain path and the
    live controller's cursor follow, so both consumers speak the one
    schema ``tune.measurements_from_events`` understands.  ``to_unix``
    maps the native monotonic clock onto the unix epoch (0 leaves
    timestamps on the native clock — fine for consumers that only read
    durations)."""
    canon = []
    for e in raw:
        ev = {
            "name": e["name"],
            "src": "native",
            "ts_us": (e["t"] + to_unix) * 1e6 + clock_offset_us,
            "dur_us": e["dur_s"] * 1e6,
            "wait_us": e["wait_s"] * 1e6,
            "dispatch_us": e.get("queue_s", 0.0) * 1e6,
            "bytes": e["bytes"],
            "peer": e["peer"],
            "tag": e["tag"],
            "algo": e["algo"],
        }
        # wire_bytes defaults to the logical bytes everywhere (schema
        # compatibility with pre-quantization recordings); carry it
        # only when it differs — a quantized collective's compressed
        # payload
        wb = e.get("wire_bytes", e["bytes"])
        if wb != e["bytes"]:
            ev["wire_bytes"] = wb
        # transport tier: carried only on a hierarchical collective's
        # per-leg events ("intra"/"inter"), absent on whole-op and
        # flat events — pre-topology recordings stay schema-identical
        if e.get("tier"):
            ev["tier"] = e["tier"]
        # transport syscall count: carried only when the native library
        # writes it (uring-generation .so) — pre-uring recordings stay
        # schema-identical, and a fake 0 never masquerades as data
        if "syscalls" in e:
            ev["syscalls"] = e["syscalls"]
        # link-layer recovery events the op absorbed (self-healing
        # retries/reconnects it rode through); nonzero only under
        # MPI4JAX_TPU_RETRY with an actual fault, so fault-free
        # recordings stay schema-identical
        if e.get("retries"):
            ev["retries"] = e["retries"]
        canon.append(ev)
    return canon


def _pull_native() -> None:
    """Drain the native ring into the canonical accumulator."""
    if _state.lib is None or _state.native_acc is None:
        return
    _, dropped = _native.counts(_state.lib)
    raw = _native.drain(_state.lib)
    _state.native_dropped = dropped
    _state.native_acc.extend(canonicalize_native(
        raw, _state.unix0 - _state.steady0, _state.clock_offset_us))


def events():
    """Everything recorded so far (native + ops spans), canonical form,
    sorted by aligned timestamp."""
    _pull_native()
    out = []
    if _state.native_acc is not None:
        out.extend(_state.native_acc.snapshot())
    if _state.spans is not None:
        out.extend(_state.spans.snapshot())
    out.sort(key=lambda e: e["ts_us"])
    return out


def dropped() -> dict:
    """Exact overflow accounting per source."""
    nat = _state.native_dropped
    if _state.native_acc is not None:
        nat += _state.native_acc.dropped
    return {
        "native": nat,
        "ops": _state.spans.dropped if _state.spans is not None else 0,
    }


def link_counters():
    """Process-total self-healing link counters (retries, reconnects,
    dup_dropped, crc_errors, replayed, heartbeats) from the live
    transport, or ``None`` without one (mesh-tier / pure-span use, or a
    library predating the link layer).  These are cumulative totals,
    not ring entries — they survive ring overflow and drains."""
    if _state.lib is None:
        return None
    return _native.link_counters(_state.lib)


def rank() -> int:
    return _state.rank


def size() -> int:
    return _state.size


def clock_offset_us() -> float:
    return _state.clock_offset_us


def generation() -> int:
    """The elastic world generation this recording belongs to."""
    return _state.generation
