"""Recording dump files: what a rank writes at finalize, what the
launcher/profile CLI read back.

Pure stdlib.  A *part* file (``<base>.rank<r>.json``) is one rank's
recording — metadata plus the canonical event list, timestamps already
on the aligned job timeline.  The merged artifact is the Chrome trace
(``_trace.merge_parts``); both carry enough per-event detail (bytes,
algorithm, duration) for ``python -m mpi4jax_tpu.tune --from-trace`` to
re-derive the algorithm cache from a real run.
"""

from __future__ import annotations

import glob
import json
import os

PART_VERSION = 1


def part_path(base: str, rank: int) -> str:
    return f"{base}.rank{int(rank)}.json"


def part_paths(base: str):
    """Every rank part written for ``base``, rank order."""
    found = glob.glob(f"{glob.escape(base)}.rank*.json")

    def _rank(p):
        tail = p[len(base):]
        digits = "".join(ch for ch in tail if ch.isdigit())
        return int(digits or 0)

    return sorted(found, key=_rank)


def write_part(base: str, *, rank: int, size: int, events,
               dropped=None, clock_offset_us=0.0, generation=0) -> str:
    """Atomically write one rank's recording; returns the path.

    ``generation`` is the elastic world generation the recording
    belongs to (0 = the original world) — an additive field, so
    pre-elastic readers and parts are unaffected."""
    path = part_path(base, rank)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    payload = {
        "version": PART_VERSION,
        "rank": int(rank),
        "size": int(size),
        "generation": int(generation),
        "clock_offset_us": float(clock_offset_us),
        "dropped": dict(dropped or {}),
        "events": list(events),
    }
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)
    return path


def load_part(path: str) -> dict:
    with open(path) as f:
        data = json.load(f)
    if not isinstance(data, dict) or "events" not in data:
        raise ValueError(f"{path} is not an obs recording part")
    if int(data.get("version", -1)) != PART_VERSION:
        raise ValueError(
            f"{path} has recording version {data.get('version')!r}, "
            f"expected {PART_VERSION}")
    return data


def load_events(path: str):
    """(events, world_size) from EITHER a part file or a merged Chrome
    trace — the tuner's ``--from-trace`` accepts both.  Chrome spans are
    mapped back to canonical events (metadata and phase slices are
    skipped)."""
    events, size, _gens = load_events_meta(path)
    return events, size


def load_events_meta(path: str):
    """(events, world_size, generations) — like :func:`load_events`
    plus the set of elastic world generations the file's recording
    belongs to: a part file carries exactly one; a merged Chrome trace
    reports every per-rank generation it merged (``otherData.
    generations``).  Pre-elastic files report ``{0}``.  The tuner's
    ``--from-trace`` uses this to keep pre- and post-shrink timings
    from pooling into one median."""
    with open(path) as f:
        data = json.load(f)
    if isinstance(data, dict) and "events" in data and "version" in data:
        # a part file: the version gate applies here too — silently
        # reading a future format with v1 semantics would render a
        # wrong table instead of the intended loud error
        if int(data.get("version", -1)) != PART_VERSION:
            raise ValueError(
                f"{path} has recording version {data.get('version')!r}, "
                f"expected {PART_VERSION}")
        return (list(data["events"]), int(data.get("size", 1)),
                {int(data.get("generation", 0))})
    if isinstance(data, dict) and "traceEvents" in data:
        events = []
        for ev in data["traceEvents"]:
            if ev.get("ph") != "X" or ev.get("cat") == "phase":
                continue
            args = ev.get("args") or {}
            evd = {
                "name": ev.get("name", "?"),
                "src": "native" if ev.get("tid") == 0 else "ops",
                "ts_us": float(ev.get("ts", 0.0)),
                "dur_us": float(ev.get("dur", 0.0)),
                "wait_us": float(args.get("wait_us", 0.0)),
                "bytes": int(args.get("bytes", 0)),
                "peer": int(args.get("peer", -1)),
                "tag": int(args.get("tag", 0)),
                "algo": args.get("algo"),
            }
            if "wire_bytes" in args:
                evd["wire_bytes"] = int(args["wire_bytes"])
            if args.get("tier"):
                evd["tier"] = args["tier"]  # hierarchical leg label
            if args.get("phase"):
                evd["phase"] = args["phase"]  # serving phase label
            events.append(evd)
        other = data.get("otherData") or {}
        gens = {int(g) for g in (other.get("generations") or {}).values()}
        return events, int(other.get("world_size", 1)), (gens or {0})
    raise ValueError(
        f"{path} is neither an obs recording part nor a Chrome trace")
